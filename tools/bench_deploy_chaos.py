"""Deploy chaos-ramp bench: the train→serve loop end to end, under
load, churn and one injected rollout fault.

One seeded arrival trace ramps offered QPS 10× (low → 10× → low, the
diurnal curve compressed).  It is served twice:

1. **baseline** — a fixed fleet at max size, no chaos, no deployments:
   the reference tokens;
2. **chaos run** — the fleet starts at ONE replica with the
   :class:`SloAutoscaler` (backed by a :class:`PoolArbiter` borrowing
   hosts from a training-mesh ledger) scaling it up the ramp and back
   down the far side; mid-ramp a trainer checkpoint (same weights)
   lands and the :class:`DeploymentController` rolls it across the
   fleet while traffic flows — with a ``servable_corrupt@0`` chaos
   fault corrupting the FIRST rollout's artifact, forcing a full
   rollback (the next poll re-exports and succeeds); shed submits
   retry through ``serving.client.backoff_submit``.

The row is the proof, enforced (RuntimeError, not a number):
``requests_lost`` must be 0, every request delivered, tokens
byte-identical to the baseline (greedy trace — neither the swap, the
failover-drain scale-down, nor the rollback may perturb a single
token), ≥1 scale-up, ≥1 scale-down, exactly one rolled-back and one
deployed rollout attempt, and the pool arbiter's borrow/return ledger
balanced.  Scale/rollout/rollback timings ride the ``autoscale`` /
``deploy`` telemetry records on stdout (``tools/metrics_to_md.py``
renders the tables).

Standalone: ``python tools/bench_deploy_chaos.py`` (CPU-safe; the jnp
reference paged-attention path serves).
"""

from __future__ import annotations

import os
import shutil
import sys
import tempfile
import time

if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if _repo not in sys.path:
        sys.path.insert(0, _repo)
    _tools = os.path.dirname(os.path.abspath(__file__))
    if _tools not in sys.path:
        sys.path.insert(0, _tools)

import numpy as np  # noqa: E402

MAX_REPLICAS = 3
LOW_QPS = 20.0
HIGH_QPS = 200.0  # the 10× ramp peak
CONTROL_PERIOD_S = 0.02  # autoscaler step / controller poll cadence


def make_ramp_trace(n_requests: int, seed: int = 0):
    """(prompt, max_new_tokens, arrival_offset_s) triples — Poisson
    arrivals whose rate ramps LOW → 10× → LOW in thirds (the diurnal
    curve compressed to bench scale), ragged prompts and lengths."""
    rng = np.random.default_rng(seed)
    out, t = [], 0.0
    for i in range(n_requests):
        frac = i / n_requests
        rate = HIGH_QPS if 1 / 3 <= frac < 2 / 3 else LOW_QPS
        t += float(rng.exponential(1.0 / rate))
        plen = int(rng.integers(4, 13))
        prompt = rng.integers(1, 255, size=plen).tolist()
        max_new = int(rng.integers(4, 17))
        out.append((prompt, max_new, t))
    return out


def _scfg(seed: int):
    from paddle_tpu.serving.scheduler import ServingConfig

    return ServingConfig(
        max_slots=4, page_size=16, num_pages=96, max_prompt_len=16,
        max_new_tokens=32, prefill_batch=4, seed=seed)


def run_baseline(cfg, params, trace, seed: int = 0):
    """The reference run: a fixed fleet at max size, no chaos, no
    deployments — same trace, same backoff client."""
    from paddle_tpu.serving.client import backoff_submit
    from paddle_tpu.serving.fleet import FleetConfig, build_local_fleet
    from paddle_tpu.telemetry import MetricsRegistry

    reg = MetricsRegistry("bench_deploy_baseline")
    router = build_local_fleet(cfg, params, _scfg(seed), n=MAX_REPLICAS,
                               registry=reg, fleet=FleetConfig())
    for rep in router.replicas:
        rep.engine.generate([[1, 2, 3]] * 2, max_new_tokens=2)

    t0 = time.perf_counter()

    def pump_for(delay_s: float) -> None:
        end = time.perf_counter() + delay_s
        while time.perf_counter() < end:
            if not router.pump():
                time.sleep(2e-4)

    for prompt, max_new, arrival in trace:
        while time.perf_counter() - t0 < arrival:
            if not router.pump():
                time.sleep(2e-4)
        backoff_submit(router, prompt, max_new_tokens=max_new,
                       seed=seed, wait=pump_for)
    router.run_until_idle()
    results = router.results()
    stats = router.stats()
    if stats["requests_lost"] != 0 or len(results) != len(trace):
        raise RuntimeError(
            f"baseline lost requests: {stats['requests_lost']} lost, "
            f"{len(results)}/{len(trace)} delivered — {stats}")
    return results


def run_chaos(cfg, params, trace, seed: int = 0, sink=None):
    """The proving run: 1 replica + autoscaler + pool arbiter +
    deployment controller + one servable_corrupt rollout fault."""
    from paddle_tpu.deploy import (
        AutoscalePolicy,
        DeploymentController,
        PoolArbiter,
        SloAutoscaler,
    )
    from paddle_tpu.resilience.chaos import ChaosSchedule
    from paddle_tpu.resilience.elastic import ElasticCoordinator
    from paddle_tpu.serving.client import backoff_submit
    from paddle_tpu.serving.fleet import FleetConfig, build_local_fleet
    from paddle_tpu.telemetry import MetricsRegistry
    from paddle_tpu.trainer.checkpoint import save_checkpoint

    reg = MetricsRegistry("bench_deploy_chaos")
    if sink is not None:
        reg.add_sink(sink)
    chaos = ChaosSchedule("servable_corrupt@0", registry=reg)
    router = build_local_fleet(cfg, params, _scfg(seed), n=1,
                               registry=reg, chaos=chaos,
                               fleet=FleetConfig())
    router.replicas[0].engine.generate([[1, 2, 3]] * 2, max_new_tokens=2)

    arbiter = PoolArbiter(
        total_hosts=4, serving_hosts=1, min_trainer_hosts=1,
        elastic=ElasticCoordinator(registry=reg), registry=reg)
    autoscaler = SloAutoscaler(
        router,
        AutoscalePolicy(min_replicas=1, max_replicas=MAX_REPLICAS,
                        up_queue_per_replica=4.0,
                        down_queue_per_replica=0.5, idle_hold_s=0.3,
                        cooldown_up_s=0.05, cooldown_down_s=0.2),
        arbiter=arbiter, registry=reg)

    work = tempfile.mkdtemp(prefix="bench_deploy_chaos_")
    ckpt_dir = os.path.join(work, "ckpts")
    controller = DeploymentController(
        ckpt_dir, os.path.join(work, "servable"), router, cfg,
        registry=reg)

    flat = {}

    def flatten(d, prefix=""):
        for k, v in d.items():
            if isinstance(v, dict):
                flatten(v, f"{prefix}{k}/")
            else:
                flat[f"{prefix}{k}"] = np.asarray(v)

    flatten(params)

    t0 = time.perf_counter()
    last_control = [0.0]

    def control() -> None:
        now = time.perf_counter()
        if now - last_control[0] < CONTROL_PERIOD_S:
            return
        last_control[0] = now
        autoscaler.step()
        controller.poll()

    def pump_for(delay_s: float) -> None:
        end = time.perf_counter() + delay_s
        while time.perf_counter() < end:
            if not router.pump():
                time.sleep(2e-4)
            control()

    try:
        for i, (prompt, max_new, arrival) in enumerate(trace):
            while time.perf_counter() - t0 < arrival:
                if not router.pump():
                    time.sleep(2e-4)
                control()
            backoff_submit(router, prompt, max_new_tokens=max_new,
                           seed=seed, wait=pump_for)
            if i == len(trace) // 2:
                # the mid-ramp checkpoint: SAME weights, so the rollout
                # must be token-invisible — the swap is what's tested,
                # not the model
                save_checkpoint(ckpt_dir, 0, flat)
            control()
        # idle out: drain the queue, let the rollout land (attempt 1
        # rolls back on the chaos corrupt, attempt 2 deploys) and the
        # autoscaler walk the fleet back down to min
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            if not router.pump():
                time.sleep(2e-4)
            control()
            s = router.stats()
            done = (s["pending"] == 0 and s["inflight"] == 0
                    and controller.deployed_uuid() is not None
                    and s["alive_replicas"] == 1)
            if done:
                break
        else:
            raise RuntimeError(
                "chaos run did not converge (drained + deployed + "
                f"scaled back to 1 replica) in time: {router.stats()}, "
                f"ledger {controller.ledger()}")
        router.run_until_idle()
    finally:
        shutil.rmtree(work, ignore_errors=True)
    return (router.results(), router.stats(), autoscaler.history(),
            controller.ledger(), arbiter)


def run_bench(n_requests: int = 48, seed: int = 0,
              sink=None) -> list[dict]:
    import jax

    from paddle_tpu.models import transformer as T

    cfg = T.TransformerConfig(
        vocab_size=256, num_layers=2, num_heads=2, embed_dim=64,
        mlp_dim=128, max_seq_len=128, remat=False)
    params = T.init_params(cfg, jax.random.key(seed))
    trace = make_ramp_trace(n_requests, seed=seed)

    base_res = run_baseline(cfg, params, trace, seed=seed)
    res, stats, actions, ledger, arbiter = run_chaos(
        cfg, params, trace, seed=seed, sink=sink)

    # -- the acceptance properties, enforced ----------------------------------
    if stats["requests_lost"] != 0 or len(res) != n_requests:
        raise RuntimeError(
            f"chaos run lost requests: {stats['requests_lost']} lost, "
            f"{len(res)}/{n_requests} delivered — {stats}")
    same = all(a.tokens == b.tokens for a, b in
               zip(sorted(base_res, key=lambda r: r.id),
                   sorted(res, key=lambda r: r.id)))
    if not same:
        raise RuntimeError(
            "scale churn / rollout / rollback changed generated tokens "
            "vs the fixed-fleet baseline — the greedy trace must be "
            "byte-identical")
    ups = [a for a in actions if a["event"] == "scale_up"]
    downs = [a for a in actions if a["event"] == "scale_down"]
    if not ups or not downs:
        raise RuntimeError(
            f"autoscaler did not ride the ramp both ways: "
            f"{len(ups)} up(s), {len(downs)} down(s) — {actions}")
    rolled = [r for r in ledger if r["outcome"] == "rolled_back"]
    deployed = [r for r in ledger if r["outcome"] == "deployed"]
    if len(rolled) != 1 or len(deployed) != 1:
        raise RuntimeError(
            f"expected exactly one rolled-back and one deployed "
            f"attempt, got {ledger}")
    shifts = arbiter.shifts()
    borrows = sum(1 for s in shifts if s["event"] == "pool_borrow")
    returns = sum(1 for s in shifts if s["event"] == "pool_return")
    if borrows != len(ups) or returns != len(downs):
        raise RuntimeError(
            f"pool ledger out of balance: {borrows} borrow(s) vs "
            f"{len(ups)} scale-up(s), {returns} return(s) vs "
            f"{len(downs)} scale-down(s) — {shifts}")

    config = (f"2L/64d transformer, {n_requests} arrivals ramping "
              f"{LOW_QPS:.0f}→{HIGH_QPS:.0f}→{LOW_QPS:.0f} QPS, fleet "
              f"1..{MAX_REPLICAS} replicas, mid-ramp rollout, one "
              f"servable_corrupt")
    return [{
        "metric": "deploy_chaos_ramp_p99_scale_up_ms",
        "value": round(max(a.get("scale_ms", 0.0) for a in ups), 1),
        "unit": "ms",
        "scale_ups": len(ups), "scale_downs": len(downs),
        "rollout_ms": round(deployed[0]["total_ms"], 1),
        "rollback_ms": round(rolled[0]["total_ms"], 1),
        "requests_lost": stats["requests_lost"],
        "shed": stats["shed"],
        "failovers": stats["failovers"],
        "tokens_identical": bool(same),
        "pool_borrows": borrows, "pool_returns": returns,
        "config": config, "vs_baseline": 0,
    }]


def main() -> None:
    from paddle_tpu.telemetry import JsonlSink

    sink = JsonlSink(sys.stdout)
    rows = run_bench(sink=sink)
    from paddle_tpu.telemetry import MetricsRegistry

    reg = MetricsRegistry("bench_deploy_chaos")
    reg.add_sink(sink)
    for r in rows:
        reg.emit(r, kind="bench")


if __name__ == "__main__":
    main()
