"""Render BENCHMARKS.md's main tables from a ``python bench.py`` JSONL
capture, so the doc rows and the driver-recorded rows are the same
experiment by construction (VERDICT r2 task 6).

Usage: python bench.py | tee /tmp/bench.jsonl
       python tools/bench_to_md.py /tmp/bench.jsonl
"""

from __future__ import annotations

import json
import sys

K40 = {  # reference-published 1x K40m ms/batch (benchmark/README.md)
    "alexnet_train_ms_per_batch_bs64": ("AlexNet", 64, 195),
    "alexnet_train_ms_per_batch_bs128": ("AlexNet", 128, 334),
    "alexnet_train_ms_per_batch_bs256": ("AlexNet", 256, 602),
    "alexnet_train_ms_per_batch_bs512": ("AlexNet", 512, 1629),
    "googlenet_train_ms_per_batch_bs64": ("GoogleNet", 64, 613),
    "googlenet_train_ms_per_batch_bs128": ("GoogleNet", 128, 1149),
    "smallnet_cifar_train_ms_per_batch_bs64": ("SmallNet (cifar)", 64, 10.46),
    "lstm_text_train_ms_per_batch_h256_bs64":
        ("LSTM text-classif h256 (seqlen 100)", 64, 83),
    "lstm_text_train_ms_per_batch_h512_bs64": ("LSTM h512", 64, 184),
    "lstm_text_train_ms_per_batch_h1280_bs64": ("LSTM h1280", 64, 641),
}


def main(path: str):
    recs = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line.startswith("{"):
                r = json.loads(line)
                recs[r["metric"]] = r

    print("## Reference benchmark tables, reproduced "
          "(regenerate: python bench.py | tee x.jsonl; "
          "python tools/bench_to_md.py x.jsonl)\n")
    print("| Model (train step) | batch | this build (v5e) | "
          "reference (K40m) | ratio |")
    print("|---|---|---|---|---|")
    for metric, (label, bs, k40) in K40.items():
        r = recs.get(metric)
        if not r:
            continue
        print(f"| {label} | {bs} | **{r['value']} ms** | {k40} ms | "
              f"{r['vs_baseline']:.0f}× |")

    print("\n## North-star configs (no published reference numbers — "
          "established here)\n")
    print("| Config | metric |")
    print("|---|---|")
    rows = [
        ("resnet50_train_img_per_sec_bs64", "ResNet-50 train bs64"),
        ("resnet50_train_img_per_sec_bs128", "ResNet-50 train bs128"),
        ("resnet50_train_img_per_sec_bs256", "ResNet-50 train bs256"),
        ("transformer_lm_124m_tokens_per_sec", "Transformer LM 124M"),
        ("nmt_attention_train_seq_per_sec", "seq2seq+attention NMT"),
        ("ctr_wide_deep_train_examples_per_sec", "Wide&Deep CTR"),
        ("ocr_crnn_ctc_train_samples_per_sec", "OCR CRNN (conv+BiLSTM+CTC)"),
    ]
    for metric, label in rows:
        r = recs.get(metric)
        if not r:
            continue
        extra = f" ({r['mfu_pct']}% MFU)" if "mfu_pct" in r else ""
        cfg = f" — {r['config']}" if "config" in r else ""
        print(f"| {label}{cfg} | **{r['value']:,.0f} {r['unit']}**{extra} |")

    sat = [(m, r) for m, r in recs.items() if m.endswith("_saturated")]
    if sat:
        print("\n## Saturated-batch rows (bench_saturation — the "
              "latency-bound verdicts completed; see the saturation "
              "section for the revision)\n")
        print("| row | value | MFU | GB/s (vs STREAM) |")
        print("|---|---|---|---|")
        for m, r in sorted(sat):
            val = (f"{r['seq_per_sec']:,.0f} seq/s ({r['value']} ms)"
                   if "seq_per_sec" in r
                   else f"{r['value']:,.0f} {r['unit']}")
            print(f"| {m.replace('_saturated', '')} | **{val}** | "
                  f"{r.get('mfu_pct', '-')}% | {r.get('achieved_gbps', '-')}"
                  f" ({r.get('hbm_pct', '-')}%) |")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "/tmp/bench.jsonl")
