"""ICI scaling harness (VERDICT r4 #7): run the dp/sp/tp/pp parallelism
grid on WHATEVER mesh exists and emit a per-step compute/collective
split per configuration.

The reference's analog is its 4-GPU scaling tables
(``benchmark/README.md:68-83``); here the same question — "what does
adding chips buy, and what does communication cost" — is answered with
jax.sharding meshes + XLA collectives instead of NCCL.

Today (single chip / no pod) the grid runs on a virtual CPU mesh:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \\
        python tools/bench_multichip.py

On a pod host the SAME command (no flags) lays the meshes over the real
chips and the split rides the profiler's device-side op durations:

    python tools/bench_multichip.py --steps 20 --layers 12 --embed 1024

Timing sources, best available first: device-side chrome-trace op
durations (collective vs compute classified by HLO op name), else
wall-clock totals with the collective INVENTORY from the compiled HLO
text — so the harness degrades gracefully on backends whose profiler
lacks per-op rows, and the collective census is exact either way.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
if _TOOLS_DIR not in sys.path:
    sys.path.insert(0, _TOOLS_DIR)

# HLO op-name prefixes that are cross-device communication
# (partition-id/replica-id are device-LOCAL and deliberately excluded)
COLLECTIVE_PREFIXES = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all", "collective-broadcast",
)


def _is_collective(name: str) -> bool:
    s = name.lower()
    return any(p in s for p in COLLECTIVE_PREFIXES)


def grid_for(n: int) -> list[dict]:
    """The parallelism configs that fit an n-device world."""
    cfgs = [{"name": "dp%d" % n, "kind": "transformer",
             "mesh": {"data": n}}]
    if n >= 4:
        cfgs.append({"name": "dp%d_tp2" % (n // 2), "kind": "transformer",
                     "mesh": {"data": n // 2, "model": 2}})
    if n >= 8:
        cfgs.append({"name": "dp%d_sp2_tp2" % (n // 4), "kind": "transformer",
                     "mesh": {"data": n // 4, "seq": 2, "model": 2}})
        cfgs.append({"name": "tp%d" % n, "kind": "transformer",
                     "mesh": {"model": n}})
        # ZeRO weight-update sharding rows: same dp mesh, sharded
        # optimizer state (zero1) / reduce-scattered grad flow (zero2) —
        # the census should show all-reduce replaced by reduce-scatter +
        # all-gather on the zero2 row
        cfgs.append({"name": "dp%d_zero1" % n, "kind": "transformer",
                     "mesh": {"data": n}, "zero": 1})
        cfgs.append({"name": "dp%d_zero2" % n, "kind": "transformer",
                     "mesh": {"data": n}, "zero": 2})
    if n >= 2:
        cfgs.append({"name": "pp%d" % min(4, n), "kind": "pipeline",
                     "stages": min(4, n)})
    return cfgs


def _build_transformer_step(mesh_axes: dict, layers: int, embed: int,
                            seq_len: int, batch_per_replica: int,
                            zero: int = 0):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from paddle_tpu.models import transformer as T
    from paddle_tpu.optimizer import Adam

    names = tuple(mesh_axes)
    shape = tuple(mesh_axes.values())
    used = int(np.prod(shape))
    devs = np.asarray(jax.devices()[:used]).reshape(shape)
    mesh = Mesh(devs, names)
    cfg = T.TransformerConfig(
        vocab_size=256, num_layers=layers, num_heads=4, embed_dim=embed,
        mlp_dim=embed * 4, max_seq_len=seq_len, remat=False,
        attn_impl="ring" if "seq" in names else "exact",
    )
    params = T.place_params(T.init_params(cfg, jax.random.key(0)), mesh, cfg)
    opt = Adam(learning_rate=1e-4)
    state = opt.init_tree(params)
    if zero >= 1:
        from paddle_tpu.parallel import zero as zero_mod

        state = zero_mod.shard_opt_state(
            state, params, mesh, param_specs=T.param_shardings(cfg))
    step = T.build_train_step(cfg, opt, mesh=mesh, zero=zero)
    b = batch_per_replica * mesh.shape.get("data", 1)
    ids = np.random.default_rng(0).integers(0, 256, (b, seq_len + 1))
    spec = P("data", None) if "data" in mesh.shape else P(None, None)
    ids = jax.device_put(jnp.asarray(ids), NamedSharding(mesh, spec))

    holder = {"params": params, "state": state}

    def run_once():
        holder["params"], holder["state"], loss = step(
            holder["params"], holder["state"], ids)
        return loss

    def hlo_text():
        return step.lower(holder["params"], holder["state"],
                          ids).compile().as_text()

    return run_once, mesh, hlo_text


def _build_pipeline_step(stages: int, width: int, batch: int):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from paddle_tpu.parallel.pipeline import pipeline_apply

    devs = np.asarray(jax.devices()[:stages]).reshape(stages)
    mesh = Mesh(devs, ("pipe",))
    r = np.random.default_rng(0)
    w = jnp.asarray(r.normal(size=(stages, width, width)).astype(np.float32) * 0.2)
    b = jnp.asarray(r.normal(size=(stages, width)).astype(np.float32) * 0.1)
    x = jnp.asarray(r.normal(size=(batch, width)).astype(np.float32))
    y = jnp.asarray(r.normal(size=(batch, width)).astype(np.float32))

    def stage_fn(p, h):
        return jnp.tanh(h @ p[0] + p[1])

    @jax.jit
    def train_step(params, x, y):
        def loss_fn(params):
            out = pipeline_apply(stage_fn, params, x, n_microbatches=4,
                                 mesh=mesh)
            return jnp.mean((out - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        return jax.tree.map(lambda p, g: p - 0.01 * g, params, grads), loss

    holder = {"params": (w, b)}

    def run_once():
        holder["params"], loss = train_step(holder["params"], x, y)
        return loss

    def hlo_text():
        return train_step.lower(holder["params"], x, y).compile().as_text()

    return run_once, mesh, hlo_text


def _collective_census_from_trace(run_once, steps: int):
    """Per-op durations from a device trace, split compute/collective.
    Returns (compute_ms, collective_ms, census) or None if the backend's
    trace has no per-op rows."""
    import jax

    if jax.devices()[0].platform == "cpu":
        return None  # CPU traces carry no XLA-Ops durations; HLO census
    try:
        from xprof import profile_step
    except ImportError:
        return None
    try:
        rows, _ = profile_step(run_once, steps=steps, top=0)
    except Exception as e:
        print(f"bench_multichip: trace census unavailable ({e}); "
              f"falling back to the HLO census", file=sys.stderr)
        return None
    if not rows:
        return None
    comp = coll = 0.0
    census: dict[str, float] = {}
    for r in rows:
        # dur_us is the CROSS-step total; r["ms"] is per-step
        ms = r.get("ms", r["dur_us"] / 1000.0 / max(steps, 1))
        name = r.get("name", "")
        if _is_collective(name):
            coll += ms
            key = name.split(".")[0].split("-start")[0].split("-done")[0]
            census[key] = census.get(key, 0.0) + ms
        else:
            comp += ms
    if comp + coll <= 0.0:
        return None  # backend trace had no usable per-op durations
    return comp, coll, census


def _collective_census_from_hlo(hlo_text_fn) -> dict[str, int]:
    """STATIC collective op inventory from the compiled HLO text (works
    on every backend).  These are program-text counts, not per-step
    execution counts: an op inside a while/fori loop body appears once
    here but executes once per iteration (e.g. pipeline_apply's permutes
    run ~n_microbatches+n_stages-1 times per step).  Per-step EXECUTION
    time comes from the trace split where available."""
    import re

    try:
        text = hlo_text_fn()
    except Exception as e:
        print(f"bench_multichip: compiled HLO text unavailable ({e}); "
              f"no static collective census", file=sys.stderr)
        return {}
    # HLO op syntax: `%name = TYPE all-reduce(...)` (TYPE may be a long
    # tuple); match the opcode immediately before its operand paren —
    # operand REFERENCES (%all-reduce.30) don't match because they carry
    # an id suffix before the paren
    # async collectives appear as -start/-done PAIRS on TPU; count each
    # op once by matching only the base or -start form
    pat = re.compile(r"\s(all-reduce|all-gather|reduce-scatter|"
                     r"collective-permute|all-to-all)"
                     r"(?:-start)?\(")
    census: dict[str, int] = {}
    for mt in pat.finditer(text):
        k = mt.group(1)
        census[k] = census.get(k, 0) + 1
    return census


def bench_config(cfg: dict, steps: int, layers: int, embed: int,
                 seq_len: int, batch_per_replica: int) -> dict:
    import jax

    if cfg["kind"] == "pipeline":
        run_once, mesh, hlo_text = _build_pipeline_step(
            cfg["stages"], width=embed, batch=8 * cfg["stages"])
    else:
        run_once, mesh, hlo_text = _build_transformer_step(
            cfg["mesh"], layers, embed, seq_len, batch_per_replica,
            zero=cfg.get("zero", 0))

    loss = run_once()  # compile
    float(np.asarray(loss).reshape(-1)[0])
    t0 = time.monotonic()
    for _ in range(steps):
        loss = run_once()
    float(np.asarray(loss).reshape(-1)[0])  # fence (tunnel-safe readback)
    wall_ms = (time.monotonic() - t0) * 1000.0 / steps

    row = {
        "config": cfg["name"],
        "mesh": cfg.get("mesh") or {"pipe": cfg.get("stages")},
        "devices": int(np.prod(list((cfg.get("mesh")
                                     or {"p": cfg.get("stages")}).values()))),
        "wall_ms_per_step": round(wall_ms, 3),
        "loss": float(np.asarray(loss).reshape(-1)[0]),
    }
    row["collectives_hlo"] = _collective_census_from_hlo(hlo_text)
    split = _collective_census_from_trace(run_once, steps=min(steps, 5))
    if split is not None:
        comp, coll, census = split
        row["compute_ms"] = round(comp, 3)
        row["collective_ms"] = round(coll, 3)
        row["collective_pct"] = round(
            100.0 * coll / max(comp + coll, 1e-9), 1)
        row["collectives"] = {k: round(v, 3) for k, v in census.items()}
    return row


def run_grid(steps: int = 8, layers: int = 2, embed: int = 64,
             seq_len: int = 64, batch_per_replica: int = 2,
             configs: list[dict] | None = None) -> list[dict]:
    """Run the grid; returns one dict per config (also usable tiny from
    the dryrun path)."""
    import jax

    n = len(jax.devices())
    rows = []
    for cfg in (configs or grid_for(n)):
        rows.append(bench_config(cfg, steps, layers, embed, seq_len,
                                 batch_per_replica))
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--embed", type=int, default=64)
    ap.add_argument("--seq_len", type=int, default=64)
    ap.add_argument("--batch_per_replica", type=int, default=2)
    args = ap.parse_args(argv)
    for row in run_grid(args.steps, args.layers, args.embed, args.seq_len,
                        args.batch_per_replica):
        print(json.dumps(row))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
