#!/usr/bin/env python
"""tools/plan_search.py — config-space feasibility pruner + static plan
ranking over the bench model families (the plan-cache seed for the
future autotuner; ROADMAP item 4).

``--enumerate`` sweeps the config grid (mesh data-axis size × zero mode
× lowering × fused_kernels × remat × seq_buckets × batch) per model
family, WITHOUT compiling or executing any step:

- each distinct (family, batch, remat) is traced ONCE to a jaxpr; every
  mesh/zero/lowering variant of it is scored analytically from that one
  trace (the same GSPMD global-shape scaling rule GL-P-MEM uses);
- infeasible points are pruned by the GL-P-MEM static byte model
  (params + zero-mode optimizer slots + activations/dp vs ``--hbm_gb``);
- survivors are ranked by the GL-P-COST roofline: primary key is
  normalized chip-time, ``step_ms × dp / batch`` (predicted step_ms
  alone would trivially crown the smallest config), with deterministic
  tie-breaks preferring the simpler plan (smaller dp, lower zero, the
  default lowering/bucketing, fused kernels on) — duplicate-cost
  variants the static model cannot distinguish must not rank randomly;
- the ranked plan is persisted as JSON (``--out``, default PLAN.json)
  with the per-family top choice and whether it matches the hand-picked
  checked-in bench config.

Trace-only: safe on a CPU dev box, no accelerator, no XLA compile.

    python tools/plan_search.py --enumerate
    python tools/plan_search.py --enumerate --families lstm --json -
    python tools/plan_search.py --enumerate --hw_profile v5p --hbm_gb 16
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the per-chip HBM budget the bench fleet's hand-picked configs were
# sized for (a v5e-class part); pass 0 to use the profile's capacity
DEFAULT_HBM_GB = 16.0

# the checked-in hand-picked bench configs (bench.py / BENCHMARKS.md) —
# the plan search's correctness anchor: on the bench budget its top
# choice should rediscover at least one of these
HAND_PICKED = {
    "transformer": {"batch": 16, "remat": False, "dp": 1, "zero": 0},
    "resnet50": {"batch": 128, "dp": 1, "zero": 0},
    "lstm": {"batch": 256, "dp": 1, "zero": 0},
}


class _MeshShim:
    """Just enough mesh for the static models: ``shape`` (dict-like) and
    ``axis_names`` — no devices, so dp>1 plans can be scored on a 1-chip
    dev box without building a real jax Mesh."""

    def __init__(self, dp: int, axis: str = "data"):
        self.shape = {axis: int(dp)}
        self.axis_names = (axis,)


# -- one trace per (family, batch, remat) ---------------------------------------


def _trace_transformer(batch: int, remat: bool) -> dict:
    import jax
    import jax.numpy as jnp

    from paddle_tpu.analysis.program import jaxpr_of
    from paddle_tpu.models import transformer as T
    from paddle_tpu.optimizer import Adam

    seq = 1024
    cfg = T.TransformerConfig(
        vocab_size=50257, num_layers=12, num_heads=12, embed_dim=768,
        mlp_dim=3072, max_seq_len=2048, dtype=jnp.float32, remat=remat,
        attn_impl="flash", attn_block_size=1024)
    params = T.init_params(cfg, jax.random.key(0))
    opt = Adam(learning_rate=1e-4, moment_dtype=jnp.bfloat16)
    opt_state = opt.init_tree(params)
    ids = np.zeros((batch, seq + 1), np.int32)
    step = T.build_train_step(cfg, opt, compute_dtype=jnp.bfloat16)
    jx = jaxpr_of(step, params, opt_state, ids)
    return {"jx": jx, "params": params, "opt_state": opt_state,
            "states": {}, "feed": {"ids": ids}, "batch": batch,
            "seq": seq, "examples": batch}


def _trace_topology(cost_fn, feed, batch: int, optimizer=None) -> dict:
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.analysis.program import jaxpr_of
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.layers import base
    from paddle_tpu.optimizer import Momentum
    from paddle_tpu.trainer.step import build_train_step

    base.reset_name_counters()
    topo = Topology(cost_fn())
    opt = optimizer or Momentum(momentum=0.9, learning_rate=0.01)
    specs = {s.name: s for s in topo.param_specs()}
    params = paddle.parameters.create(topo).as_dict()
    opt_state = opt.init(params, specs)
    states = topo.init_states()
    step = build_train_step(topo, opt, compute_dtype=jnp.bfloat16)
    args = (params, opt_state, states, feed, jax.random.key(0))
    jx = jaxpr_of(step, *args)
    return {"jx": jx, "params": params, "opt_state": opt_state,
            "states": states, "feed": feed, "batch": batch,
            "examples": batch}


def _trace_resnet50(batch: int, remat: bool = False) -> dict:
    from paddle_tpu.models import image as M

    rng = np.random.default_rng(0)
    feed = {"image": rng.normal(size=(batch, 224 * 224 * 3)).astype(
                np.float32),
            "label": rng.integers(0, 1000, size=(batch,))}
    return _trace_topology(lambda: M.resnet_cost(depth=50)[0], feed,
                           batch)


def _trace_lstm(batch: int, remat: bool = False) -> dict:
    import jax.numpy as jnp

    from paddle_tpu.core.lod import SequenceBatch
    from paddle_tpu.optimizer import Adam

    rng = np.random.default_rng(0)
    feed = {"data": SequenceBatch(
                data=rng.integers(0, 30000, size=(batch, 100)),
                length=np.full((batch,), 100, np.int32)),
            "label": rng.integers(0, 2, size=(batch,))}
    return _trace_topology(
        lambda: __import__("bench")._lstm_classify_cost(512), feed,
        batch, optimizer=Adam(learning_rate=2e-3,
                              moment_dtype=jnp.bfloat16))


# -- the grid -------------------------------------------------------------------

# (dp, zero, lowering) mesh plans: dp=1 has one lowering; dp=8 scores
# both lowering families (identical static cost — the tie-break keeps
# the partitioner default first)
_MESH_PLANS = [(1, 0, "auto"),
               (8, 0, "gspmd"), (8, 0, "explicit"),
               (8, 1, "gspmd"), (8, 1, "explicit")]

FAMILIES = {
    "transformer": {
        "trace": _trace_transformer,
        "batches": (8, 16, 32),
        "remat": (False, True),
        "fused": (True,),
        "seq_buckets": ("",),
    },
    "resnet50": {
        "trace": _trace_resnet50,
        "batches": (64, 128, 256),
        "remat": (False,),
        "fused": (True,),
        "seq_buckets": ("",),
    },
    "lstm": {
        "trace": _trace_lstm,
        "batches": (128, 256),
        "remat": (False,),
        "fused": (True, False),
        "seq_buckets": ("", "32,64,100"),
    },
}


def _tie_key(pt: dict) -> tuple:
    """Deterministic ranking key: normalized chip-time first, then the
    simpler plan wins among statically indistinguishable variants."""
    return (pt["score_chip_ms_per_example"], pt["dp"], pt["zero"],
            0 if pt["lowering"] in ("auto", "gspmd") else 1,
            0 if pt["fused_kernels"] else 1,
            0 if not pt["seq_buckets"] else 1,
            0 if not pt["remat"] else 1,
            -pt["batch"])


def enumerate_family(name: str, spec: dict, profile, hbm_gb: float,
                     log=print) -> dict:
    """Trace, prune and rank one family's grid.  Returns the family
    section of the plan JSON."""
    from paddle_tpu.analysis.cost import cost_report
    from paddle_tpu.analysis.memory import (
        activation_peak_bytes,
        opt_state_bytes_per_device,
        pallas_vmem_estimates,
        tree_bytes,
    )

    feasible: list[dict] = []
    pruned: list[dict] = []
    n_traces = 0
    for batch in spec["batches"]:
        for remat in spec["remat"]:
            t0 = time.time()
            tr = spec["trace"](batch, remat)
            n_traces += 1
            log(f"  traced {name} batch={batch} remat={remat} "
                f"({time.time() - t0:.1f}s)")
            params_b = tree_bytes(tr["params"])
            states_b = tree_bytes(tr["states"])
            feed_b = tree_bytes(tr["feed"])
            act_b = activation_peak_bytes(tr["jx"])
            pallas = pallas_vmem_estimates(tr["jx"])
            cost_cache: dict = {}
            for dp, zero, lowering in _MESH_PLANS:
                shim = _MeshShim(dp) if dp > 1 else None
                opt_b = opt_state_bytes_per_device(
                    tr["opt_state"], tr["params"], shim, zero)
                total = (params_b + opt_b + states_b
                         + feed_b // dp + act_b // dp)
                if (dp, zero) not in cost_cache:
                    cost_cache[(dp, zero)] = cost_report(
                        tr["jx"], profile=profile, mesh=shim, zero=zero,
                        params_bytes=params_b)
                cost = cost_cache[(dp, zero)]
                for fused in spec["fused"]:
                    for buckets in spec["seq_buckets"]:
                        pt = {
                            "family": name, "batch": batch,
                            "remat": remat, "dp": dp, "zero": zero,
                            "lowering": lowering,
                            "fused_kernels": fused,
                            "seq_buckets": buckets,
                            "mem_total_bytes": total,
                            "step_ms": cost["step_ms"],
                            "mfu_pct": cost["mfu_pct"],
                            "comm_ms": cost["comm_ms"],
                            "bottleneck": cost["bottleneck"],
                            "score_chip_ms_per_example":
                                cost["step_ms"] * dp / tr["examples"],
                        }
                        budget = hbm_gb * 1e9
                        if budget > 0 and total > budget:
                            pt["pruned"] = (
                                f"GL-P-MEM: {total / 1e9:.2f} GB > "
                                f"{hbm_gb:g} GB")
                            pruned.append(pt)
                        else:
                            feasible.append(pt)
            del tr  # free the traced params before the next shape
    feasible.sort(key=_tie_key)
    top = feasible[0] if feasible else None
    want = HAND_PICKED.get(name, {})
    matches = bool(top) and all(top.get(k) == v for k, v in want.items())
    return {"points": len(feasible) + len(pruned), "traces": n_traces,
            "pruned": len(pruned), "ranked": feasible,
            "pruned_points": pruned, "top": top,
            "hand_picked": want, "top_matches_bench": matches}


def build_plan(families=None, hw_profile_name: str = "v5p",
               hbm_gb: float = DEFAULT_HBM_GB, log=print) -> dict:
    from paddle_tpu.analysis.cost import hw_profile

    profile = hw_profile(hw_profile_name)
    if hbm_gb <= 0:
        hbm_gb = profile.hbm_gb
    plan: dict = {
        "schema": "paddle_tpu.plan/1",
        "hw_profile": profile.name,
        "hbm_gb": hbm_gb,
        "families": {},
    }
    total = prunedn = 0
    for name, spec in FAMILIES.items():
        if families and name not in families:
            continue
        log(f"plan_search: enumerating {name} ...")
        fam = enumerate_family(name, spec, profile, hbm_gb, log=log)
        plan["families"][name] = fam
        total += fam["points"]
        prunedn += fam["pruned"]
    plan["grid_points"] = total
    plan["pruned"] = prunedn
    return plan


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or "-h" in argv or "--help" in argv:
        print(__doc__.strip())
        return 2
    if "--enumerate" not in argv:
        print("plan_search: nothing to do (pass --enumerate)",
              file=sys.stderr)
        return 2
    argv.remove("--enumerate")

    def _opt(flag, default):
        if flag in argv:
            i = argv.index(flag)
            val = argv[i + 1]
            del argv[i:i + 2]
            return val
        return default

    out_path = _opt("--out", os.path.join(REPO, "PLAN.json"))
    hw = _opt("--hw_profile", "v5p")
    hbm_gb = float(_opt("--hbm_gb", str(DEFAULT_HBM_GB)))
    fams = _opt("--families", "")
    families = [f for f in fams.split(",") if f] or None
    quiet = "--quiet" in argv
    if quiet:
        argv.remove("--quiet")
    if argv:
        print(f"plan_search: unknown arguments {argv}", file=sys.stderr)
        return 2
    log = (lambda *a, **k: None) if quiet else print

    t0 = time.time()
    try:
        plan = build_plan(families, hw_profile_name=hw, hbm_gb=hbm_gb,
                          log=log)
    except ValueError as e:  # unknown profile/family: a usage error
        print(f"plan_search: {e}", file=sys.stderr)
        return 2
    plan["wall_s"] = round(time.time() - t0, 1)

    text = json.dumps(plan, indent=1, default=float)
    if out_path == "-":
        print(text)
    else:
        with open(out_path, "w") as f:
            f.write(text + "\n")
    for name, fam in plan["families"].items():
        top = fam["top"] or {}
        log(f"plan_search: {name}: {fam['points']} points "
            f"({fam['traces']} traces), {fam['pruned']} pruned; top = "
            f"batch {top.get('batch')} remat {top.get('remat')} "
            f"dp {top.get('dp')} zero {top.get('zero')} "
            f"({top.get('score_chip_ms_per_example', 0):.4f} "
            f"chip-ms/example, MFU {top.get('mfu_pct', 0):.1f}%)"
            + ("  [= hand-picked bench config]"
               if fam["top_matches_bench"] else ""))
    log(f"plan_search: {plan['grid_points']} grid points, "
        f"{plan['pruned']} pruned, no step compiled, "
        f"{plan['wall_s']}s" + ("" if out_path == "-"
                                else f" -> {out_path}"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
