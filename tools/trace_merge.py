#!/usr/bin/env python
"""Merge per-rank Chrome trace files into ONE Perfetto timeline.

Every rank of a ``distributed.launch`` fleet dumps its own span ring
(``--trace_dir`` -> ``trace-host<k>.json``, or a live scrape of
``/trace`` saved per rank); this tool folds them into a single
trace-event file where each rank is its own process lane (``pid`` =
rank, process_name ``rank <k>`` — replicas from ``launch --serving``
render as ``replica <k>``), so one Perfetto view shows the whole
fleet's feed/compute/fence (or queue/prefill/decode) phases side by
side, wall-clock aligned.

Usage::

    python tools/trace_merge.py LOGDIR [...]  -o merged.json
    python tools/trace_merge.py rank0.json rank1.json -o merged.json

Arguments are trace files or directories (directories are scanned for
``trace-host*.json`` / ``trace-replica*.json`` / ``*.trace.json``).
The rank of each file comes from its own metadata (``otherData.rank``,
the tracer's stamp) with the filename's ``host<k>`` as the fallback;
on a collision (two files claiming one rank — e.g. scrapes of the same
rank at two times) later files are offset to a free lane and a warning
names them.  Prints a per-rank span census.

A rank that produced a trace but recorded zero spans (tracing armed
late, ring drained by a /trace scrape) is TOLERATED: its lane merges
with a 0-span census row and the merge still succeeds.  Exit 2 only
when NO input yields any span event — the message then names which
files were empty (parsed, zero spans) vs. missing (named on the
command line but absent on disk), so "forgot --trace_spans" and
"wrong log dir" read differently.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys


def find_trace_files(args: list[str]) -> list[str]:
    files: list[str] = []
    for a in args:
        if os.path.isdir(a):
            for pat in ("trace-host*.json", "trace-replica*.json",
                        "*.trace.json"):
                files.extend(sorted(glob.glob(os.path.join(a, pat))))
        else:
            files.append(a)
    # de-dup, keep order
    seen: set[str] = set()
    out = []
    for f in files:
        if f not in seen:
            seen.add(f)
            out.append(f)
    return out


def rank_of(path: str, trace: dict) -> int | None:
    """The lane a file's events belong to: the tracer's own stamp, else
    the ``host<k>``/``replica<k>`` filename convention."""
    other = trace.get("otherData") or {}
    if isinstance(other.get("rank"), int):
        return other["rank"]
    m = re.search(r"(?:host|replica|rank)[-_]?(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else None


def merge(files: list[str], label: str = "rank") -> dict:
    """Fold trace files into one trace-event dict with one pid lane per
    rank.  Returns the merged trace; ``otherData.lanes`` maps pid ->
    source file and ``otherData.empty`` lists inputs that parsed but
    held zero span events (their lanes still exist — a rank with an
    armed-late tracer shows as an empty lane, not a hole)."""
    events: list[dict] = []
    lanes: dict[int, str] = {}
    empty: list[str] = []
    next_free = 0
    for path in files:
        with open(path) as f:
            trace = json.load(f)
        src = (trace.get("traceEvents")
               if isinstance(trace, dict) else trace) or []
        rank = rank_of(path, trace if isinstance(trace, dict) else {})
        if rank is None or rank in lanes:
            while next_free in lanes:
                next_free += 1
            if rank is not None:
                print(f"trace_merge: {path} claims lane {rank} already "
                      f"taken by {lanes[rank]}; moving it to lane "
                      f"{next_free}", file=sys.stderr)
            rank = next_free
        lanes[rank] = path
        have_name = False
        n_spans = 0
        for e in src:
            e = dict(e)
            e["pid"] = rank
            if e.get("ph") == "X":
                n_spans += 1
            if e.get("ph") == "M" and e.get("name") == "process_name":
                e["args"] = {"name": f"{label} {rank}"}
                have_name = True
            events.append(e)
        if not n_spans:
            empty.append(path)
        if not have_name:
            events.append({"name": "process_name", "ph": "M",
                           "pid": rank, "tid": 0,
                           "args": {"name": f"{label} {rank}"}})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"lanes": {str(k): v
                                    for k, v in sorted(lanes.items())},
                          "empty": empty}}


def census(merged: dict) -> dict[int, int]:
    """{pid lane: complete-event count} — the per-rank span census the
    CLI prints (and tests assert both lanes are populated from)."""
    out: dict[int, int] = {}
    for e in merged.get("traceEvents", ()):
        if e.get("ph") == "X":
            out[e.get("pid", -1)] = out.get(e.get("pid", -1), 0) + 1
    return out


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 2
    out_path = "trace_merged.json"
    if "-o" in argv:
        i = argv.index("-o")
        out_path = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    label = "rank"
    if "--label" in argv:
        i = argv.index("--label")
        label = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    files = find_trace_files(argv)
    # explicit file arguments that don't exist are MISSING (wrong path,
    # rank never dumped), distinct from files that parse to zero spans
    # (tracing armed late / ring drained) — the exit-2 message names
    # each group so the two failure modes read differently
    missing = [f for f in files if not os.path.exists(f)]
    files = [f for f in files if os.path.exists(f)]
    if not files:
        if missing:
            print(f"trace_merge: no trace files — missing: "
                  f"{', '.join(missing)}", file=sys.stderr)
        else:
            print(f"trace_merge: no trace files under {argv}",
                  file=sys.stderr)
        return 2
    merged = merge(files, label=label)
    counts = census(merged)
    empty = merged["otherData"].get("empty", [])
    if not counts:
        parts = []
        if empty:
            parts.append(f"empty (parsed, zero spans): {', '.join(empty)}")
        if missing:
            parts.append(f"missing: {', '.join(missing)}")
        print("trace_merge: inputs contained no span events — "
              + "; ".join(parts or ["no inputs"]), file=sys.stderr)
        return 2
    with open(out_path, "w") as f:
        json.dump(merged, f)
    # zero-span lanes are tolerated: they merged, they just census 0
    for pid in merged["otherData"]["lanes"]:
        counts.setdefault(int(pid), 0)
    total = sum(counts.values())
    lanes = ", ".join(f"{label} {k}: {v}" for k, v in sorted(counts.items()))
    print(f"trace_merge: {total} spans across {len(counts)} lane(s) "
          f"({lanes}) -> {out_path}")
    if missing:
        print(f"trace_merge: warning — named but missing: "
              f"{', '.join(missing)}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
