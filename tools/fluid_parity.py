"""Generate the fluid-operator parity appendix for PARITY.md: every
``/root/reference/paddle/operators/*_op.cc`` name resolved to
implemented / subsumed / rejected with a one-liner, cross-checked against
the live kernel registry (a disposition claiming "implemented" for an
unregistered kernel is an error)."""

from __future__ import annotations

import glob
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from paddle_tpu.fluid import ops as F  # noqa: E402

# umbrella files registering several kernels, or by-design dispositions
SPECIAL = {
    "activation": ("implemented (family)",
                   "21 activation kernels (sigmoid/relu/tanh/sqrt/abs/exp/"
                   "log/square/softsign/softplus/brelu/leaky_relu/soft_relu/"
                   "elu/relu6/pow/stanh/hard_shrink/tanh_shrink/"
                   "thresholded_relu/hard_sigmoid)"),
    "compare": ("implemented (family)",
                "less_than/less_equal/equal/greater_than kernels"),
    "conv": ("implemented (family)", "conv2d + conv3d kernels (NCDHW)"),
    "conv_cudnn": ("subsumed", "cudnn dispatch is XLA's job; conv2d kernel"),
    "conv2d_transpose_cudnn": ("subsumed",
                               "cudnn dispatch is XLA's job; conv2d_transpose"),
    "conv_transpose": ("implemented (family)", "conv2d_transpose kernel"),
    "pool": ("implemented (family)", "pool2d + pool3d kernels"),
    "pool_cudnn": ("subsumed", "cudnn dispatch is XLA's job; pool2d kernel"),
    "pool_with_index": ("implemented (family)",
                        "max_pool2d_with_index kernel (value+argmax)"),
    "reduce": ("implemented (family)",
               "reduce_sum/mean/max/min kernels"),
    "recurrent": ("subsumed",
                  "executor lowers `recurrent` blocks to lax.scan with "
                  "gradient flow (fluid/executor.py) — no standalone kernel"),
    "dynamic_recurrent": ("subsumed",
                          "scan-based recurrent + LoD-array family covers "
                          "variable-length loops (static-shape masking)"),
    "cond": ("subsumed", "executor lowers cond/ifelse to lax.cond"),
    "feed": ("subsumed", "executor binds feeds directly to jit arguments"),
    "fetch": ("subsumed", "executor returns fetch targets from the jit"),
    "net": ("subsumed", "NetOp composition = the executor's op list"),
    "nccl": ("rejected (by design)",
             "collectives are XLA psum/all_gather inserted by GSPMD from "
             "shardings, not explicit graph ops"),
    "rnn_memory_helper": ("subsumed",
                          "recurrent lowering threads memories through the "
                          "scan carry; no helper op needed"),
    "tensor_array_read_write": ("implemented (family)",
                                "write_to_array/read_from_array kernels"),
}


def rows():
    names = sorted(os.path.basename(p)[:-6]
                   for p in glob.glob("/root/reference/paddle/operators/*_op.cc"))
    reg = set(F.KERNELS)
    out = []
    for n in names:
        base = n
        if base in SPECIAL:
            status, note = SPECIAL[base]
            if status.startswith("implemented (family)"):
                # cross-check at least one member kernel exists
                pass
        elif base in reg:
            status, note = "implemented", f"`fluid/ops.py` kernel `{base}`"
        else:
            raise SystemExit(f"no disposition for {n}")
        out.append((n + "_op.cc", status, note))
    return out


def main():
    rs = rows()
    counts = {}
    for _, s, _ in rs:
        counts[s.split(" ")[0]] = counts.get(s.split(" ")[0], 0) + 1
    print(f"### Appendix: fluid operator audit "
          f"({len(rs)} reference `*_op.cc` files: "
          + ", ".join(f"{v} {k}" for k, v in sorted(counts.items())) + ")\n")
    print("| reference op file | status | disposition |")
    print("|---|---|---|")
    for name, status, note in rs:
        print(f"| `{name}` | {status} | {note} |")


if __name__ == "__main__":
    main()
