"""Transformer LM step-time lab — reproduce the 124M baseline and measure
each candidate optimisation in isolation (VERDICT r2 task 2: where does the
107.8 ms go when the MXU-bound floor is ~31 ms?).

Usage: PYTHONPATH=/root/repo:/root/.axon_site python tools/bench_lm.py [variant ...]
Variants: see main()'s dispatch table (baseline, noremat, exact, dots, mp,
mp_full, mp_norm, mp16, mp32, bs16, bs32) or "breakdown".
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.models import transformer as T
from paddle_tpu.optimizer import Adam

VOCAB = 50257


def two_point(step_fn, warmup=2, n1=3, n2=13):
    def run(n):
        t0 = time.perf_counter()
        c = None
        for _ in range(n):
            c = step_fn()
        float(np.asarray(c).reshape(-1)[0])
        return time.perf_counter() - t0

    run(warmup)
    t1 = min(run(n1) for _ in range(2))
    t2 = min(run(n2) for _ in range(2))
    return max(t2 - t1, 1e-9) / (n2 - n1) * 1000.0


def gpt2_cfg(**kw):
    base = dict(
        vocab_size=VOCAB, num_layers=12, num_heads=12, embed_dim=768,
        mlp_dim=3072, max_seq_len=2048, dtype=jnp.bfloat16, remat=True,
        attn_impl="flash", attn_block_size=512,
    )
    base.update(kw)
    return T.TransformerConfig(**base)


def n_params(params):
    return sum(x.size for x in jax.tree.leaves(params))


def run_variant(name: str, cfg, bs=8, seqlen=1024,
                opt=None, compute_dtype=None):
    key = jax.random.key(0)
    params = T.init_params(cfg, key)
    N = n_params(params)
    opt = opt or Adam(learning_rate=1e-4)
    opt_state = opt.init_tree(params)
    ids = jax.device_put(
        np.random.default_rng(0).integers(0, cfg.vocab_size,
                                          size=(bs, seqlen + 1)))

    jstep = T.build_train_step(cfg, opt, compute_dtype=compute_dtype)
    state = {"p": params, "o": opt_state}

    def one():
        state["p"], state["o"], loss = jstep(state["p"], state["o"], ids)
        return loss

    ms = two_point(one)
    tokens = bs * seqlen
    # 6ND + attention FLOPs (2*2*2 * L * B*T^2*HD per train step, causal /2)
    attn_fl = 12 * cfg.num_layers * bs * seqlen * seqlen * cfg.embed_dim / 2
    fl = 6.0 * N * tokens + attn_fl
    mfu = fl / (ms / 1e3) / 197e12
    print(f"{name:16s} {ms:8.2f} ms/step  {tokens / ms * 1000:10.0f} tok/s  "
          f"mfu {mfu * 100:5.1f}%  (N={N / 1e6:.1f}M)")
    return ms


def breakdown(cfg, bs=8, seqlen=1024):
    """Segment timing: full step vs grad-only vs fwd(+head, no CE) vs
    optimizer-only."""
    key = jax.random.key(0)
    params = T.init_params(cfg, key)
    opt = Adam(learning_rate=1e-4)
    opt_state = opt.init_tree(params)
    ids = jax.device_put(
        np.random.default_rng(0).integers(0, cfg.vocab_size,
                                          size=(bs, seqlen + 1)))

    lf = lambda p: T.loss_fn(cfg, p, ids)

    # fwd loss only
    fwd = jax.jit(lf)
    ms_fwd = two_point(lambda: fwd(params))
    print(f"fwd+loss only      {ms_fwd:8.2f} ms")

    # fwd through the LM head but without the CE loss
    def body_only(p):
        logits = T.forward(cfg, p, ids[:, :-1])
        return jnp.sum(logits.astype(jnp.float32))
    f2 = jax.jit(body_only)
    ms_body = two_point(lambda: f2(params))
    print(f"fwd incl head(sum) {ms_body:8.2f} ms")

    # grad only (no optimizer)
    vg = jax.jit(jax.value_and_grad(lf))
    ms_vg = two_point(lambda: vg(params)[0])
    print(f"value_and_grad     {ms_vg:8.2f} ms")

    # optimizer alone on unit grads
    grads = jax.tree.map(jnp.ones_like, params)
    grads = jax.device_put(grads)

    def opt_only(p, o, g):
        return opt.apply_tree(g, p, o)
    jopt = jax.jit(opt_only)
    st = {"p": params, "o": opt_state}

    def one():
        st["p"], st["o"] = jopt(st["p"], st["o"], grads)
        return st["o"]["step"]
    ms_opt = two_point(one)
    print(f"optimizer only     {ms_opt:8.2f} ms")


def main():
    variants = sys.argv[1:] or ["baseline"]
    if variants[0] == "breakdown":
        breakdown(gpt2_cfg())
        return
    for v in variants:
        if v == "baseline":
            run_variant(v, gpt2_cfg())
        elif v == "noremat":
            run_variant(v, gpt2_cfg(remat=False))
        elif v == "exact":
            run_variant(v, gpt2_cfg(attn_impl="exact"))
        elif v == "exact_noremat":
            run_variant(v, gpt2_cfg(attn_impl="exact", remat=False))
        elif v == "dots":
            run_variant(v, gpt2_cfg(remat="dots"))
        elif v == "mp":
            # proper mixed precision: f32 masters, bf16 compute
            run_variant(v, gpt2_cfg(remat="dots", dtype=jnp.float32),
                        compute_dtype=jnp.bfloat16)
        elif v == "mp_full":
            run_variant(v, gpt2_cfg(remat=True, dtype=jnp.float32),
                        compute_dtype=jnp.bfloat16)
        elif v == "mp_norm":
            run_variant(v, gpt2_cfg(remat=False, dtype=jnp.float32),
                        compute_dtype=jnp.bfloat16)
        elif v == "mp16":
            run_variant(v, gpt2_cfg(remat="dots", dtype=jnp.float32),
                        compute_dtype=jnp.bfloat16, bs=16)
        elif v == "bs16":
            run_variant(v, gpt2_cfg(remat="dots"), bs=16)
        elif v == "bs32":
            run_variant(v, gpt2_cfg(remat="dots"), bs=32)
        elif v == "mp32":
            run_variant(v, gpt2_cfg(remat="dots", dtype=jnp.float32),
                        compute_dtype=jnp.bfloat16, bs=32)
        elif v == "mom16":
            # the bench shape (bs16, no remat, f32 masters) with bf16
            # Adam moments — the HBM lever on the ~5 ms Adam line
            run_variant(v, gpt2_cfg(remat=False, dtype=jnp.float32),
                        compute_dtype=jnp.bfloat16, bs=16,
                        opt=Adam(learning_rate=1e-4,
                                 moment_dtype=jnp.bfloat16))
        elif v == "mom16_bs24":
            run_variant(v, gpt2_cfg(remat=False, dtype=jnp.float32),
                        compute_dtype=jnp.bfloat16, bs=24,
                        opt=Adam(learning_rate=1e-4,
                                 moment_dtype=jnp.bfloat16))
        elif v == "mp16_ref":
            # f32-moment control at the identical bench shape
            run_variant(v, gpt2_cfg(remat=False, dtype=jnp.float32),
                        compute_dtype=jnp.bfloat16, bs=16)
        else:
            print(f"unknown variant {v}")


if __name__ == "__main__":
    main()
