#!/usr/bin/env python
"""Render a goodput ledger (``ledger.jsonl``) as a wall-clock account.

Every run with ``--goodput_ledger`` appends one ``kind="ledger"``
record (telemetry/goodput.py) to ``<ledger_dir>/ledger.jsonl``; this
tool renders each record as a badput-attribution table with a bar per
bucket, the serving cost-per-token split when the run served, and —
with two or more records in the file — a run-over-run goodput trend
line, so "where did the wall-clock go" is one command away::

    python tools/goodput_report.py runs/ledger.jsonl
    python tools/goodput_report.py metrics.jsonl   # any record stream

Exit codes: 0 rendered, 2 no ledger records found / unreadable input.
"""

from __future__ import annotations

import json
import sys

BAR_WIDTH = 40


def load_ledgers(path: str) -> list[dict]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("kind") == "ledger" or (
                    "buckets_s" in rec and "wall_s" in rec):
                out.append(rec)
    return out


def _bar(share: float) -> str:
    n = int(round(share * BAR_WIDTH))
    return "█" * n + "·" * (BAR_WIDTH - n)


def render_one(rec: dict, index: int | None = None) -> None:
    wall = float(rec.get("wall_s") or 0.0)
    frac = rec.get("goodput_fraction")
    head = f"ledger[{index}]" if index is not None else "ledger"
    ts = rec.get("ts")
    host = rec.get("host")
    extras = []
    if host is not None:
        extras.append(f"host {host}")
    if ts is not None:
        extras.append(f"ts {ts:.0f}")
    print(f"{head}: wall {wall:.3f} s"
          + (f", goodput {frac * 100:.1f}%" if frac is not None else "")
          + (f" ({', '.join(extras)})" if extras else ""))
    buckets = rec.get("buckets_s") or {}
    width = max((len(k) for k in buckets), default=10)
    for name, secs in buckets.items():
        share = secs / wall if wall else 0.0
        print(f"  {name:{width}s} {secs:10.3f} s {share * 100:6.1f}% "
              f"{_bar(share)}")
    if rec.get("spans_dropped"):
        print(f"  (ring dropped {rec['spans_dropped']} spans — the "
              f"account may undercount classified buckets into idle)")
    serving = rec.get("serving") or {}
    if serving:
        print(f"  serving: {serving.get('tokens', 0):.0f} tokens, "
              f"cost/token {serving.get('cost_per_token_s', 0):.6g} s "
              f"(prefill {serving.get('cost_per_token_prefill_s', 0):.6g}"
              f" + decode {serving.get('cost_per_token_decode_s', 0):.6g}"
              f"), queue/token "
              f"{serving.get('cost_per_token_queue_s', 0):.6g} s, "
              f"KV occupancy {serving.get('kv_page_s', 0):.3f} page·s")


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 2
    try:
        ledgers = load_ledgers(argv[0])
    except OSError as e:
        print(f"goodput_report: {e}", file=sys.stderr)
        return 2
    if not ledgers:
        print(f"goodput_report: no ledger records in {argv[0]} "
              f"(runs write them with --goodput_ledger)", file=sys.stderr)
        return 2
    for i, rec in enumerate(ledgers):
        if i:
            print()
        render_one(rec, index=i if len(ledgers) > 1 else None)
    if len(ledgers) > 1:
        fracs = [r.get("goodput_fraction") for r in ledgers]
        trend = " -> ".join(f"{f * 100:.1f}%" if f is not None else "?"
                            for f in fracs)
        print(f"\ngoodput trend over {len(ledgers)} runs: {trend}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
