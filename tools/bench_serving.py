"""Serving ablation: continuous batching vs naive static batching on a
synthetic Poisson arrival trace.

Both modes run the SAME engine, model, requests, arrival times and
sampling seed — the only difference is ``ServingConfig.static_batching``
(admit only into an idle engine; finished sequences hold their slot
until the whole batch drains — what a batch ``Inference`` loop over the
old capi surface would do).  Rows report end-to-end generated tokens/sec
and p99 TTFT per mode plus the speedup ratio; continuous batching wins
because retired slots are refilled from the queue every step instead of
idling until the batch's slowest member finishes.

Standalone: ``python tools/bench_serving.py [--long]`` (CPU-safe: the
jnp reference paged-attention path serves; the Pallas kernel is the TPU
fast path).  ``bench.py`` shells out to this script so the rows ride the
normal bench stream.  ``--long`` behind bench marker conventions: more
requests + longer generations for stabler numbers.
"""

from __future__ import annotations

import os
import sys
import time

if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if _repo not in sys.path:
        sys.path.insert(0, _repo)

import numpy as np


def make_trace(n_requests: int, seed: int = 0, rate_per_s: float = 200.0,
               max_new_lo: int = 4, max_new_hi: int = 40):
    """(prompt, max_new_tokens, arrival_offset_s) triples — Poisson
    arrivals (exponential gaps), ragged prompts and generation lengths."""
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / rate_per_s, size=n_requests)
    arrivals = np.cumsum(gaps)
    out = []
    for i in range(n_requests):
        plen = int(rng.integers(4, 17))
        prompt = rng.integers(1, 255, size=plen).tolist()
        max_new = int(rng.integers(max_new_lo, max_new_hi + 1))
        out.append((prompt, max_new, float(arrivals[i])))
    return out


def _decode_steps(reg) -> int:
    h = reg.get("serve_decode_step_ms")
    s = h.summary() if h is not None else None
    return int(s["count"]) if s else 0


def run_mode(cfg, params, trace, static: bool, seed: int = 0):
    """Feed the trace (real sleeps between arrivals) through an engine;
    returns (tokens_per_sec, p99_ttft_ms, total_tokens, decode_steps,
    results).  ``decode_steps`` is the load-independent measure: the
    trace and scheduler are deterministic, so the step count — where
    static batching's padded-drain waste shows up — is exact."""
    from paddle_tpu.serving.engine import ServingEngine
    from paddle_tpu.serving.scheduler import ServingConfig
    from paddle_tpu.telemetry import MetricsRegistry

    reg = MetricsRegistry("bench_serving")
    scfg = ServingConfig(
        max_slots=8, page_size=16, num_pages=128, max_prompt_len=16,
        max_new_tokens=48, prefill_batch=8 if static else 4, seed=seed,
        static_batching=static)
    eng = ServingEngine(cfg, params, scfg, registry=reg)
    # pay every compile signature before timing (prefill, decode)
    eng.generate([[1, 2, 3]] * 2, max_new_tokens=2)
    warm_steps = _decode_steps(reg)

    t0 = time.perf_counter()
    for prompt, max_new, arrival in trace:
        # real-time arrival replay: step the engine while waiting
        while time.perf_counter() - t0 < arrival:
            if not eng.step():
                time.sleep(2e-4)
        eng.submit(prompt, max_new_tokens=max_new)
    eng.run_until_idle()
    elapsed = time.perf_counter() - t0
    results = eng.results()
    total = sum(len(r.tokens) for r in results)
    ttfts = [r.metrics["ttft_ms"] for r in results]
    ttfts.sort()
    p99 = ttfts[min(int(round(0.99 * (len(ttfts) - 1))), len(ttfts) - 1)]
    return (total / elapsed, p99, total, _decode_steps(reg) - warm_steps,
            results)


def run_bench(n_requests: int = 24, seed: int = 0, max_new_hi: int = 40,
              pairs: int = 3) -> list[dict]:
    import jax

    from paddle_tpu.models import transformer as T

    cfg = T.TransformerConfig(
        vocab_size=256, num_layers=2, num_heads=2, embed_dim=64,
        mlp_dim=128, max_seq_len=128, remat=False)
    params = T.init_params(cfg, jax.random.key(seed))
    trace = make_trace(n_requests, seed=seed, max_new_hi=max_new_hi)

    # interleaved continuous/static PAIRS, published as the MEDIAN pair
    # by wall ratio (the bench_input_pipeline convention): both runs of
    # a pair see the same background load, and the median resists one
    # noisy pair; the decode-step counts are deterministic either way
    runs = [(run_mode(cfg, params, trace, static=False, seed=seed),
             run_mode(cfg, params, trace, static=True, seed=seed))
            for _ in range(pairs)]
    runs.sort(key=lambda cs: cs[0][0] / max(cs[1][0], 1e-9))
    ((cont_tps, cont_p99, cont_tok, cont_steps, cont_res),
     (stat_tps, stat_p99, stat_tok, stat_steps, stat_res)) \
        = runs[len(runs) // 2]
    # both modes generate the SAME tokens (same seed/key derivation) —
    # the ablation changes scheduling only
    same = all(a.tokens == b.tokens for a, b in
               zip(sorted(cont_res, key=lambda r: r.id),
                   sorted(stat_res, key=lambda r: r.id)))
    base_cfg = (f"2L/64d transformer, {n_requests} Poisson arrivals, "
                f"8 slots, page 16")
    return [
        {"metric": "serving_continuous_tokens_per_sec",
         "value": round(cont_tps, 1), "unit": "tok/s",
         "p99_ttft_ms": round(cont_p99, 1), "tokens": cont_tok,
         "decode_steps": cont_steps,
         "config": base_cfg + ", continuous batching", "vs_baseline": 0},
        {"metric": "serving_static_tokens_per_sec",
         "value": round(stat_tps, 1), "unit": "tok/s",
         "p99_ttft_ms": round(stat_p99, 1), "tokens": stat_tok,
         "decode_steps": stat_steps,
         "config": base_cfg + ", static batching", "vs_baseline": 0},
        {"metric": "serving_continuous_vs_static_speedup",
         "value": round(cont_tps / max(stat_tps, 1e-9), 2), "unit": "x",
         "tokens_identical": bool(same),
         # the wall ratio is load-sensitive; the step ratio is the
         # deterministic structural advantage (fewer fixed-cost decode
         # steps for the same tokens)
         "decode_step_ratio": round(stat_steps / max(cont_steps, 1), 2),
         "config": base_cfg, "vs_baseline": 0},
    ]


def main() -> None:
    long = "--long" in sys.argv
    # the long trace widens the generation-length spread: static batching
    # drains every batch at its slowest member's length, so the waste —
    # and the continuous engine's advantage — grows with the spread
    rows = (run_bench(n_requests=64, max_new_hi=48, pairs=3) if long
            else run_bench(n_requests=24))
    from paddle_tpu.telemetry import JsonlSink, MetricsRegistry

    reg = MetricsRegistry("bench_serving")
    reg.add_sink(JsonlSink(sys.stdout))
    for r in rows:
        reg.emit(r, kind="bench")


if __name__ == "__main__":
    main()
