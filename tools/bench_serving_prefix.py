"""Per-token serving cost ablation: prefix caching + chunked prefill
through the paged KV engine, measured at FLEET level.

Workload A (cache ablation): a shared-system-prompt trace — every
request is the SAME 48-token system prompt plus a unique ragged tail,
the dominant production shape prefix caching targets.  Both arms run
the identical 2-replica fleet, trace, arrival times and sampling seed
with chunked prefill on; the only difference is ``--prefix_cache``.
Cache-on maps the resident system-prompt pages into each new request's
page table and prefills only the tail, so the row reports the
recompute-FLOPs-saved fraction (deterministic, from the
``serve_prefill_flops_saved`` counter) next to the wall p99 TTFT at the
same offered QPS.  Greedy tokens must be byte-identical across arms AND
against the flags-off engine (today's trajectory).

Workload B (chunking row): a long-prompt + short-prompt mix replayed
with ``--prefill_chunk_tokens`` off and on — chunking interleaves the
long prompt's prefill with resident decode steps instead of stalling
them behind one monolithic pass.

Standalone: ``python tools/bench_serving_prefix.py [--long]`` (CPU-safe:
the jnp paged paths serve; Pallas is the TPU fast path).  ``bench.py``
shells out to this script so the rows ride the normal bench stream.
"""

from __future__ import annotations

import os
import sys
import time

if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if _repo not in sys.path:
        sys.path.insert(0, _repo)

import numpy as np

SYSTEM_PROMPT_LEN = 48  # 3 full pages of 16 — the shareable head


def make_shared_prefix_trace(n_requests: int, seed: int = 0,
                             rate_per_s: float = 120.0):
    """(prompt, max_new, arrival_s): one fixed system prompt + unique
    ragged tails, Poisson arrivals."""
    rng = np.random.default_rng(seed)
    head = rng.integers(1, 255, size=SYSTEM_PROMPT_LEN).tolist()
    arrivals = np.cumsum(rng.exponential(1.0 / rate_per_s, size=n_requests))
    out = []
    for i in range(n_requests):
        tail = rng.integers(1, 255, size=int(rng.integers(4, 13))).tolist()
        out.append((head + tail, int(rng.integers(4, 17)),
                    float(arrivals[i])))
    return out


def make_long_prompt_trace(n_requests: int, seed: int = 0,
                           rate_per_s: float = 60.0):
    """Alternating long (96-token) and short prompts — the shape where
    a monolithic prefill stalls the decode stream."""
    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate_per_s, size=n_requests))
    out = []
    for i in range(n_requests):
        plen = 96 if i % 2 == 0 else int(rng.integers(4, 17))
        prompt = rng.integers(1, 255, size=plen).tolist()
        out.append((prompt, int(rng.integers(4, 13)), float(arrivals[i])))
    return out


def run_fleet_mode(cfg, params, trace, seed: int = 0, n_replicas: int = 2,
                   **scfg_kw):
    """Replay the trace (real sleeps) through a local fleet; returns
    (tokens_per_sec, p99_ttft_ms, results, registry)."""
    from paddle_tpu.serving.fleet import build_local_fleet
    from paddle_tpu.serving.scheduler import ServingConfig
    from paddle_tpu.telemetry import MetricsRegistry

    reg = MetricsRegistry("bench_serving_prefix")
    scfg = ServingConfig(
        max_slots=4, page_size=16, num_pages=128, max_prompt_len=112,
        max_new_tokens=16, prefill_batch=4, seed=seed, **scfg_kw)
    router = build_local_fleet(cfg, params, scfg, n=n_replicas,
                               registry=reg)
    # pay every compile signature before timing; a 3-token prompt has
    # no full page, so nothing lands in the prefix cache
    for rep in router.replicas:
        rep.engine.generate([[255, 255, 255]] * 2, max_new_tokens=2)

    t0 = time.perf_counter()
    for prompt, max_new, arrival in trace:
        while time.perf_counter() - t0 < arrival:
            if not router.pump():
                time.sleep(2e-4)
        router.submit(prompt, max_new_tokens=max_new, temperature=0.0)
    router.run_until_idle()
    elapsed = time.perf_counter() - t0
    results = sorted(router.results(), key=lambda r: r.id)
    total = sum(len(r.tokens) for r in results)
    ttfts = sorted(r.metrics["ttft_ms"] for r in results)
    p99 = ttfts[min(int(round(0.99 * (len(ttfts) - 1))), len(ttfts) - 1)]
    return total / elapsed, p99, results, reg


def _tokens(results):
    return [r.tokens for r in results]


def run_bench(n_requests: int = 24, seed: int = 0,
              pairs: int = 3) -> list[dict]:
    import jax

    from paddle_tpu.models import transformer as T

    cfg = T.TransformerConfig(
        vocab_size=256, num_layers=2, num_heads=2, embed_dim=64,
        mlp_dim=128, max_seq_len=160, remat=False)
    params = T.init_params(cfg, jax.random.key(seed))
    param_count = sum(int(x.size) for x in jax.tree.leaves(params))

    # ---- workload A: shared system prompt, cache on vs off ----------------
    trace = make_shared_prefix_trace(n_requests, seed=seed)
    # flags-off identity reference (today's monolithic-prefill path)
    _, _, plain_res, _ = run_fleet_mode(cfg, params, trace, seed=seed)
    runs = [(run_fleet_mode(cfg, params, trace, seed=seed,
                            prefix_cache=True, prefill_chunk_tokens=16),
             run_fleet_mode(cfg, params, trace, seed=seed,
                            prefill_chunk_tokens=16))
            for _ in range(pairs)]
    # median pair by TTFT ratio (both runs of a pair share background
    # load; the FLOPs split is deterministic across pairs)
    runs.sort(key=lambda ab: ab[0][1] / max(ab[1][1], 1e-9))
    ((on_tps, on_p99, on_res, on_reg),
     (off_tps, off_p99, off_res, off_reg)) = runs[len(runs) // 2]

    same = (_tokens(on_res) == _tokens(off_res) == _tokens(plain_res))
    prompt_tokens = sum(r.metrics["prompt_tokens"] for r in on_res)
    total_prefill_flops = 2.0 * param_count * prompt_tokens
    flops_saved = on_reg.counter("serve_prefill_flops_saved").value()
    saved_frac = flops_saved / max(total_prefill_flops, 1e-9)
    hit_tokens = int(on_reg.counter("serve_prefix_hit_tokens").value())

    base_cfg = (f"2L/64d transformer, 2-replica fleet, {n_requests} "
                f"Poisson arrivals, {SYSTEM_PROMPT_LEN}-token shared "
                f"system prompt, page 16, chunk 16")
    rows = [
        {"metric": "serving_prefix_cache_on_tokens_per_sec",
         "value": round(on_tps, 1), "unit": "tok/s",
         "p99_ttft_ms": round(on_p99, 1), "hit_tokens": hit_tokens,
         "config": base_cfg + ", prefix_cache on", "vs_baseline": 0},
        {"metric": "serving_prefix_cache_off_tokens_per_sec",
         "value": round(off_tps, 1), "unit": "tok/s",
         "p99_ttft_ms": round(off_p99, 1),
         "config": base_cfg + ", prefix_cache off", "vs_baseline": 0},
        {"metric": "serving_prefix_cache_prefill_flops_saved",
         "value": round(saved_frac * 100.0, 1), "unit": "%",
         "hit_tokens": hit_tokens, "prompt_tokens": prompt_tokens,
         "p99_ttft_ratio_off_over_on":
             round(off_p99 / max(on_p99, 1e-9), 2),
         "tokens_identical": bool(same),
         "config": base_cfg, "vs_baseline": 0},
    ]

    # ---- workload B: long prompts, chunking off vs on ---------------------
    ltrace = make_long_prompt_trace(max(n_requests // 2, 8), seed=seed)
    lruns = [(run_fleet_mode(cfg, params, ltrace, seed=seed,
                             prefill_chunk_tokens=32),
              run_fleet_mode(cfg, params, ltrace, seed=seed))
             for _ in range(pairs)]
    lruns.sort(key=lambda ab: ab[0][1] / max(ab[1][1], 1e-9))
    ((ck_tps, ck_p99, ck_res, _),
     (mono_tps, mono_p99, mono_res, _)) = lruns[len(lruns) // 2]
    lsame = _tokens(ck_res) == _tokens(mono_res)
    lcfg = ("2L/64d transformer, 2-replica fleet, alternating 96-token/"
            "short prompts, page 16")
    rows.append(
        {"metric": "serving_chunked_prefill_p99_ttft_ms",
         "value": round(ck_p99, 1), "unit": "ms",
         "monolithic_p99_ttft_ms": round(mono_p99, 1),
         "chunked_tokens_per_sec": round(ck_tps, 1),
         "monolithic_tokens_per_sec": round(mono_tps, 1),
         "tokens_identical": bool(lsame),
         "config": lcfg + ", chunk 32 vs whole-prompt",
         "vs_baseline": 0})
    return rows


def main() -> None:
    long = "--long" in sys.argv
    rows = (run_bench(n_requests=48, pairs=3) if long
            else run_bench(n_requests=24))
    from paddle_tpu.telemetry import JsonlSink, MetricsRegistry

    reg = MetricsRegistry("bench_serving_prefix")
    reg.add_sink(JsonlSink(sys.stdout))
    for r in rows:
        reg.emit(r, kind="bench")


if __name__ == "__main__":
    main()
