"""Sharded-embedding CTR ablation on a forced-8-device host mesh.

Trains the SAME wide&deep CTR model (two categorical tables, sparse
row-lazy Momentum) two ways from identical initial parameters and
identical feeds —

- ``replicated-dense``: no mesh, ``fused_kernels=off`` — every device
  would hold a full table copy (the one-device dense baseline);
- ``sharded-fused``: a ``{data:2, model:4}`` mesh with the tables
  row-sharded over ``model`` and lookups routed through the TPP fused
  path (``fused_kernels=on``) —

and emits one ``*_fused_ablation_speedup`` row carrying ms/step both
ways, the per-device table byte census of the sharded arm (runtime
addressable-shard sum AND the static GL-P-MEM model — they must agree),
and the trajectory check: per-step costs must match bit-identically or
within a documented tolerance (CPU lowering reorders float
accumulation across the sharded program; the fused routing itself
resolves to the jnp reference off-TPU).  A divergence beyond the bound
raises — a broken sharded path must not report a speedup.

Standalone: ``python tools/bench_embedding.py`` (forces
JAX_PLATFORMS=cpu + 8 host devices BEFORE jax imports).  ``bench.py``
shells out to this script so the row rides the normal bench stream;
``tools/metrics_to_md.py`` renders it in the fused-kernel ablation
table.  On the CPU testbed the speedup column reads WELL below 1x —
eight virtual devices on one physical socket pay real collective
overhead with no real ICI — so the row's job there is the memory story
(4x table bytes/device reduction) and the trajectory contract; the TPU
capture is where the gather/scatter kernels and the 4-way HBM win
actually land (BENCH_r05 anchor caveat, ROADMAP.md).
"""

from __future__ import annotations

import os
import sys

if __name__ == "__main__":  # force the virtual mesh BEFORE jax imports
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags_env = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags_env:
        os.environ["XLA_FLAGS"] = (
            flags_env + " --xla_force_host_platform_device_count=8")
    _repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if _repo not in sys.path:
        sys.path.insert(0, _repo)

import numpy as np

TRAJ_TOL = 5e-3  # documented bound (see BENCHMARKS.md fused-ablation rows)


def run_ablation(steps: int = 6, warmup: int = 2, vocab: int = 2048,
                 emb_dim: int = 16, wide_dim: int = 16,
                 batch: int = 32) -> list[dict]:
    import time

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu.analysis import memory as mem
    from paddle_tpu.core import flags
    from paddle_tpu.layers import base
    from paddle_tpu.models.ctr import wide_and_deep_ctr
    from paddle_tpu.optimizer import Momentum
    from paddle_tpu.parallel import mesh as mesh_mod
    from paddle_tpu.trainer.step import build_train_step

    base.reset_name_counters()
    # vocab % 4 == 0 so pad_vocab_to adds no rows: both arms share the
    # exact same parameter shapes AND initial values
    cost, _, _ = wide_and_deep_ctr(
        wide_dim=wide_dim, categorical_vocab_sizes=[vocab, vocab],
        embedding_size=emb_dim, hidden_sizes=(32,), pad_vocab_to=4)
    topo = paddle.topology.Topology(cost)
    params0 = {k: np.asarray(v)
               for k, v in paddle.parameters.create(topo).as_dict().items()}
    specs = {s.name: s for s in topo.param_specs()}
    emb_names = sorted(n for n in params0 if n.startswith("emb_"))
    table_total = sum(p.size * p.dtype.itemsize
                      for n, p in params0.items() if n in emb_names)

    # ONE feed sequence for both arms — the trajectory check must see
    # numerics, not data
    rs = np.random.default_rng(11)
    feeds = []
    for _ in range(warmup + steps):
        wide = np.zeros((batch, wide_dim), np.float32)
        for r in range(batch):
            wide[r, rs.integers(0, wide_dim, size=3)] = 1.0
        feeds.append({
            "wide_input": wide,
            "cat_0": rs.integers(0, vocab, size=(batch,)),
            "cat_1": rs.integers(0, vocab, size=(batch,)),
            "label": rs.integers(0, 2, size=(batch,)),
        })

    def run(mode):
        snap = flags.snapshot_raw()
        try:
            flags.set("fused_kernels",
                      "on" if mode == "sharded" else "off")
            opt = Momentum(momentum=0.9, learning_rate=0.05)
            if mode == "sharded":
                ctx = mesh_mod.MeshContext(
                    mesh=mesh_mod.make_mesh({"data": 2, "model": 4}))
                params = ctx.place_params(
                    {k: jnp.asarray(v) for k, v in params0.items()}, specs)
                opt_state = ctx.replicate(opt.init(params, specs))
                states = ctx.replicate(topo.init_states())
                prep = ctx.shard_batch
            else:
                ctx = None
                params = {k: jnp.asarray(v) for k, v in params0.items()}
                opt_state = opt.init(params, specs)
                states = topo.init_states()
                prep = lambda f: f  # noqa: E731
            step = build_train_step(topo, opt, mesh=ctx)
            key = jax.random.key(0)
            costs, wall = [], 0.0
            for i, f in enumerate(feeds):
                feed = prep({k: jnp.asarray(v) for k, v in f.items()})
                t0 = time.monotonic()
                params, opt_state, states, c, _ = step(
                    params, opt_state, states, feed, key)
                c = float(c)
                if i >= warmup:
                    wall += time.monotonic() - t0
                costs.append(c)
            return wall * 1000.0 / steps, np.asarray(costs), params, ctx
        finally:
            flags.restore_raw(snap)

    ms_rep, traj_rep, _, _ = run("replicated")
    ms_sh, traj_sh, params_sh, ctx = run("sharded")

    identical = bool(np.array_equal(traj_rep, traj_sh))
    max_rel = float(np.max(np.abs(traj_rep - traj_sh)
                           / np.maximum(np.abs(traj_rep), 1e-9)))
    if not identical and max_rel > TRAJ_TOL:
        raise RuntimeError(
            f"sharded CTR trajectory diverged from replicated-dense "
            f"(max rel diff {max_rel:.2e} over {len(traj_rep)} steps)")

    # per-device table bytes of the sharded arm, counted BOTH ways
    dev0 = ctx.mesh.devices.flat[0]
    census = 0
    for n in emb_names:
        for sh in params_sh[n].addressable_shards:
            if sh.device == dev0:
                census += (int(np.prod(sh.data.shape))
                           * params_sh[n].dtype.itemsize)
    table_specs = {
        n: (P(*specs[n].sharding) if specs[n].sharding else P())
        for n in emb_names
    }
    static = mem.params_bytes_per_device(
        {n: params_sh[n] for n in emb_names}, ctx.mesh, table_specs)
    if static != census:
        raise RuntimeError(
            f"GL-P-MEM static table bytes/device {static} != runtime "
            f"census {census}")

    n_dev = int(ctx.mesh.devices.size)
    return [{
        "metric": "ctr_embedding_fused_ablation_speedup",
        "value": round(ms_rep / max(ms_sh, 1e-9), 2), "unit": "x",
        "unfused_ms": round(ms_rep, 3), "fused_ms": round(ms_sh, 3),
        "unfused_steps_per_sec": round(1000.0 / max(ms_rep, 1e-9), 1),
        "fused_steps_per_sec": round(1000.0 / max(ms_sh, 1e-9), 1),
        "trajectory_identical": identical,
        "trajectory_max_rel_diff": max_rel,
        "table_bytes_total": int(table_total),
        "table_bytes_per_device": int(census),
        "table_bytes_per_device_static": int(static),
        "table_shard_factor": round(table_total / max(census, 1), 1),
        "devices": n_dev,
        "config": f"wide&deep CTR, 2x[{vocab},{emb_dim}] tables, "
                  f"bs {batch}, replicated-dense vs dp2/ep4 sharded-fused",
        "vs_baseline": 0,
    }]


def main() -> int:
    rows = run_ablation()
    from paddle_tpu.telemetry import JsonlSink, MetricsRegistry

    reg = MetricsRegistry("bench_embedding")
    reg.add_sink(JsonlSink(sys.stdout))
    for r in rows:
        reg.emit(r, kind="bench")
    return 0


if __name__ == "__main__":
    sys.exit(main())
