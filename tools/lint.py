#!/usr/bin/env python
"""tools/lint.py — the graftlint CI entry point.

A thin driver over ``python -m paddle_tpu.analysis`` (the codebase
static-analysis suite: swallow-all excepts, threaded-subsystem lock
audit, lock-order cycles, env-registration, telemetry schema drift,
kernel reference twins, PRNG key discipline) that adds git awareness:

  python tools/lint.py              # repo-wide (what tier-1 runs)
  python tools/lint.py --changed    # only files touched vs HEAD
                                    # (staged + unstaged + untracked)
  python tools/lint.py --changed origin/main   # ...vs a base ref

``--changed`` mode skips the stale-baseline check and the corpus-global
kernel pass (a subset can't evaluate either).  Exit 1 on any
unsuppressed finding — and, on full runs, on any STALE baseline entry
(the message names the dead fid so the suppression gets cleaned up).
All other arguments are forwarded verbatim (``--json``, ``--passes``,
``--baseline``, ``--locks``).
"""

from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def changed_files(base: str | None = None) -> list[str] | None:
    """Repo-relative paths touched vs ``base`` (default: HEAD),
    including staged and untracked files.  Returns None when git
    cannot answer (shallow clone without the base ref, no git at all)
    — the caller must then run repo-wide, NOT treat it as clean."""
    out: set[str] = set()
    diff = ["git", "-C", REPO, "diff", "--name-only"]
    cmds = [diff + [base] if base else diff,
            diff + ["--cached"],
            ["git", "-C", REPO, "ls-files", "--others",
             "--exclude-standard"]]
    for cmd in cmds:
        try:
            res = subprocess.run(cmd, capture_output=True, text=True,
                                 check=True)
        except (subprocess.CalledProcessError, OSError) as e:
            print(f"lint: {' '.join(cmd)} failed ({e}); falling back to "
                  f"a repo-wide run", file=sys.stderr)
            return None
        out.update(line.strip() for line in res.stdout.splitlines()
                   if line.strip())
    return sorted(f for f in out if f.endswith(".py"))


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    sys.path.insert(0, REPO)
    from paddle_tpu.analysis.__main__ import main as analysis_main

    if "--changed" in argv:
        i = argv.index("--changed")
        base = None
        if i + 1 < len(argv) and not argv[i + 1].startswith("-"):
            base = argv[i + 1]
            del argv[i + 1]
        del argv[i]
        files = changed_files(base)
        if files is None:
            pass  # git couldn't answer: run the full suite instead
        elif not files:
            print("lint: no changed .py files")
            return 0
        else:
            argv += ["--files"] + files
    return analysis_main(argv)


if __name__ == "__main__":
    sys.exit(main())
