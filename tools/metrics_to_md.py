"""Summarize a JSONL telemetry stream (the ``paddle_tpu.metrics`` schema)
into markdown: a per-step table with loss/latency/throughput/MFU, an
aggregate row, the comm-bytes breakdown, and any bench-kind rows.

The stream is whatever a JSONL sink captured — ``SGD.train`` /
``trainer/cli.py`` step records (``--metrics_jsonl=PATH`` or
``metrics.configure(jsonl=...)``) and/or ``python bench.py`` output
(bench rows flow through the same sink API).  For the BENCHMARKS.md
reference tables specifically, use ``tools/bench_to_md.py`` on the same
capture.

Usage: python tools/metrics_to_md.py /path/to/metrics.jsonl [--last N]
"""

from __future__ import annotations

import json
import sys


def _fmt(v, nd=2):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:,.{nd}f}"
    return str(v)


def load(path: str) -> list[dict]:
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                pass  # torn tail line of a live file
    return records


def step_table(steps: list[dict], last: int | None = None) -> None:
    if last:
        shown = steps[-last:]
        if len(shown) < len(steps):
            print(f"_showing the last {len(shown)} of {len(steps)} steps_\n")
    else:
        shown = steps
    has_tok = any("tokens_per_sec" in r for r in shown)
    has_hbm = any("hbm_gbps" in r for r in shown)
    has_wait = any("input_wait_ms" in r for r in shown)
    has_stall = any("host_stall_ms" in r for r in shown)
    has_pad = any("padding_ratio" in r for r in shown)
    hdr = ["step", "pass", "loss", "step ms", "ex/s"]
    if has_tok:
        hdr.append("tok/s")
    hdr.append("MFU %")
    if has_hbm:
        hdr.append("HBM GB/s")
    if has_wait:
        hdr.append("in-wait ms")
    if has_stall:
        hdr.append("stall ms")
    if has_pad:
        hdr.append("pad %")
    print("| " + " | ".join(hdr) + " |")
    print("|" + "---|" * len(hdr))
    for r in shown:
        row = [str(r.get("step", "-")), str(r.get("pass_id", "-")),
               _fmt(r.get("loss"), 5), _fmt(r.get("step_ms")),
               _fmt(r.get("examples_per_sec"), 1)]
        if has_tok:
            row.append(_fmt(r.get("tokens_per_sec"), 0))
        row.append(_fmt(r.get("mfu_pct")))
        if has_hbm:
            row.append(_fmt(r.get("hbm_gbps")))
        if has_wait:
            # ⚠ = host-bound step: input wait exceeds 20% of step time,
            # i.e. the device idled for the feed — raise prefetch depth
            # or move preprocessing into the reader
            row.append(_fmt(r.get("input_wait_ms"))
                       + (" ⚠" if _host_bound(r) else ""))
        if has_stall:
            row.append(_fmt(r.get("host_stall_ms")))
        if has_pad:
            # ⚠ = padding-bound feed: >25% of the fed timesteps are
            # padding — bucket the reader by length (--seq_buckets)
            pr = r.get("padding_ratio")
            row.append((_fmt(pr * 100, 1) if pr is not None else "-")
                       + (" ⚠" if _padding_bound(r) else ""))
        print("| " + " | ".join(row) + " |")

    n = len(steps)
    ms = [r["step_ms"] for r in steps if "step_ms" in r]
    exs = [r["examples_per_sec"] for r in steps if "examples_per_sec" in r]
    mfu = [r["mfu_pct"] for r in steps if "mfu_pct" in r]
    print(f"\n**{n} steps** · step ms min/mean/max = "
          f"{_fmt(min(ms))}/{_fmt(sum(ms) / len(ms))}/{_fmt(max(ms))}"
          if ms else f"\n**{n} steps**", end="")
    if exs:
        print(f" · mean {_fmt(sum(exs) / len(exs), 1)} ex/s", end="")
    if mfu:
        print(f" · mean MFU {_fmt(sum(mfu) / len(mfu))}%", end="")
    print()
    bound = [r for r in steps if _host_bound(r)]
    if bound:
        waits = [r["input_wait_ms"] for r in bound]
        ids = ", ".join(str(r.get("step", "?")) for r in bound[:12])
        more = f" (+{len(bound) - 12} more)" if len(bound) > 12 else ""
        print(f"\n**⚠ {len(bound)}/{n} steps host-bound** (input wait > "
              f"20% of step time): steps {ids}{more} · worst wait "
              f"{_fmt(max(waits))} ms — the input pipeline is starving "
              f"the device; raise --prefetch or vectorize the reader.")
    padded = [r for r in steps if _padding_bound(r)]
    if padded:
        worst = max(r["padding_ratio"] for r in padded)
        print(f"\n**⚠ {len(padded)}/{n} steps padding-bound** (>25% of "
              f"fed timesteps are padding, worst "
              f"{_fmt(worst * 100, 1)}%) — bucket the reader by length "
              f"(--seq_buckets / reader.bucket_by_length) so the "
              f"recurrent sweep stops burning flops on pad rows.")


def _host_bound(r: dict) -> bool:
    """input wait exceeding 20% of step time = the device idled on input."""
    wait, ms = r.get("input_wait_ms"), r.get("step_ms")
    return bool(wait and ms and wait > 0.2 * ms)


def _padding_bound(r: dict) -> bool:
    """>25% padded timesteps = a quarter of the recurrent flops/bytes
    ran on padding; the reader should bucket by length."""
    pr = r.get("padding_ratio")
    return bool(pr is not None and pr > 0.25)


def _census_by_kind(comm: dict) -> dict:
    """Per-kind rollup of an {"op/axis": bytes} map (standalone twin of
    ``paddle_tpu.telemetry.census_by_kind`` — this tool must run on a
    bare checkout without importing the package)."""
    out: dict = {}
    for key, nbytes in comm.items():
        kind, _, axis = key.partition("/")
        row = out.setdefault(kind, {"bytes": 0.0, "sites": 0, "axes": []})
        row["bytes"] += float(nbytes)
        row["sites"] += 1
        if axis and axis not in row["axes"]:
            row["axes"].append(axis)
    return out


def comm_table(steps: list[dict]) -> None:
    comm = None
    for r in reversed(steps):  # counters are cumulative: latest wins
        if r.get("comm_bytes"):
            comm = r["comm_bytes"]
            break
    if not comm:
        return
    print("\n## Collective traffic (per-step bytes, traced)\n")
    print("| collective/axis | bytes/step |")
    print("|---|---|")
    for key, v in sorted(comm.items(), key=lambda kv: -kv[1]):
        print(f"| {key} | {v:,.0f} |")
    # the per-kind census: under ZeRO-2 the gradient flow's all_reduce
    # row drops to (near) zero, replaced by reduce_scatter + all_gather
    # at 1/n per-device payload — the collective swap, visible at a
    # glance
    census = _census_by_kind(comm)
    total = sum(r["bytes"] for r in census.values()) or 1.0
    print("\n## Collective census (per kind)\n")
    print("| kind | bytes/step/device | share | call sites | axes |")
    print("|---|---|---|---|---|")
    for kind, row in sorted(census.items(), key=lambda kv: -kv[1]["bytes"]):
        print(f"| {kind} | {row['bytes']:,.0f} "
              f"| {100.0 * row['bytes'] / total:.1f}% "
              f"| {row['sites']} | {', '.join(row['axes'])} |")
    if "reduce_scatter" in census and \
            census.get("all_reduce", {}).get("bytes", 0.0) \
            < 0.01 * census["reduce_scatter"]["bytes"]:
        print("\n_reduce-scatter carries the gradient flow (all-reduce "
              "≈ 0): the weight update is ZeRO-sharded._")


def recovery_table(faults: list[dict], recoveries: list[dict]) -> None:
    """Render the schema /3 fault-tolerance stream: one row per injected/
    handled fault and per supervisor restart, with a loud flag on any
    run that needed a restart — a dirty run must not read as clean."""
    if not faults and not recoveries:
        return
    print("\n## Faults & recovery\n")
    if recoveries:
        worst = max(r.get("recovery_ms", 0) or 0 for r in recoveries)
        print(f"**⚠ run restarted {len(recoveries)} time(s)** (worst "
              f"supervisor recovery {_fmt(float(worst))} ms) — the "
              f"trajectory is checkpoint-replayed, but investigate the "
              f"faults below.\n")
    print("| event | detail | pass | batch | loss / recovery ms |")
    print("|---|---|---|---|---|")
    for r in faults:
        print(f"| fault | {r.get('fault', '-')} | {r.get('pass_id', '-')} "
              f"| {r.get('batch_id', '-')} | {_fmt(r.get('loss'), 5)} |")
    for r in recoveries:
        print(f"| restart #{r.get('restart', '?')} "
              f"| {r.get('error', '-')} | - | - "
              f"| {_fmt(r.get('recovery_ms'))} |")


def elastic_table(events: list[dict]) -> None:
    """Render the schema /6 elastic-fleet stream: one row per live mesh
    rebuild (host loss / scale-up), with a loud flag on any recovery
    that had to fall back to a cursor checkpoint — a fallback means the
    lost host's shard was unrecoverable and progress was replayed, so
    it must not read as a clean live reshard."""
    if not events:
        return
    print("\n## Elastic events\n")
    print("| event | dp degree | recovery ms | shard source "
          "| pass | batch |")
    print("|---|---|---|---|---|---|")
    fallbacks = []
    for r in events:
        src = r.get("shard_source", "-")
        if src == "checkpoint":
            fallbacks.append(r)
            src += " ⚠"
        print(f"| {r.get('event', '-')} "
              f"| {r.get('old_dp', '?')} → {r.get('new_dp', '?')} "
              f"| {_fmt(r.get('recovery_ms'))} | {src} "
              f"| {r.get('pass_id', '-')} | {r.get('batch_id', '-')} |")
    worst = max((r.get("recovery_ms", 0) or 0 for r in events),
                default=0)
    print(f"\n**{len(events)} elastic rebuild(s)** · worst recovery "
          f"{_fmt(float(worst))} ms — training continued in-process; "
          f"no fleet restart.")
    if fallbacks:
        cursors = ", ".join(
            f"pass {r.get('replay_cursor', {}).get('pass_id', '?')} "
            f"batch {r.get('replay_cursor', {}).get('batch_id', '?')}"
            for r in fallbacks)
        print(f"\n**⚠ {len(fallbacks)} checkpoint-fallback "
              f"recover{'y' if len(fallbacks) == 1 else 'ies'}** — live "
              f"shards were unrecoverable and the trajectory replayed "
              f"from {cursors}; work since those cursors was redone.  "
              f"Shorten --checkpoint_batch_period if this recurs.")


def fleet_table(events: list[dict]) -> None:
    """Render the schema /8 serving-fleet stream: one row per fleet
    event (replica_down / swap / swap_rollback), then the newest
    availability summary — with loud flags on lost requests and
    rolled-back swaps, which must never read as a healthy fleet."""
    if not events:
        return
    print("\n## Serving fleet\n")
    rows = [r for r in events if r.get("event") != "summary"]
    if rows:
        print("| event | detail |")
        print("|---|---|")
        for r in rows:
            ev = r.get("event", "-")
            if ev == "replica_down":
                detail = (f"replica {r.get('replica', '?')} "
                          f"({r.get('reason', '?')}) — "
                          f"{r.get('requeued', 0)} request(s) re-queued"
                          + (f", {r['failed']} failed ⚠"
                             if r.get("failed") else ""))
            elif ev == "swap":
                detail = (f"servable `{r.get('servable', '?')}` rolled "
                          f"across {len(r.get('replicas') or {})} "
                          f"replica(s), zero downtime")
            elif ev == "swap_rollback":
                detail = (f"⚠ servable `{r.get('servable', '?')}` "
                          f"REFUSED ({r.get('error', '?')}); rolled "
                          f"back {len(r.get('rolled_back') or [])} "
                          f"replica(s)")
            elif ev == "replica_added":
                detail = (f"replica {r.get('replica', '?')} joined "
                          f"(cloned from replica "
                          f"{r.get('source', '?')}) — fleet now "
                          f"{r.get('alive', '?')} alive")
            elif ev == "replica_retired":
                detail = (f"replica {r.get('replica', '?')} retired "
                          f"({r.get('reason', '?')}) — "
                          f"{r.get('requeued', 0)} request(s) "
                          f"re-queued, fleet now "
                          f"{r.get('alive', '?')} alive")
            else:
                detail = str({k: v for k, v in r.items()
                              if k not in ("event", "kind", "schema",
                                           "ts", "host")})
            print(f"| {ev} | {detail} |")
    summaries = [r for r in events if r.get("event") == "summary"]
    for s in summaries[-1:]:
        lost = s.get("requests_lost", 0)
        print(f"\n**fleet summary** · {s.get('submitted', 0)} submitted "
              f"· {s.get('delivered', 0)} delivered "
              f"· {s.get('failovers', 0)} failover(s) "
              f"· {s.get('shed', 0)} shed "
              f"· {s.get('swaps', 0)} swap(s) "
              f"· {s.get('alive_replicas', '?')} replica(s) alive "
              f"· requests lost: "
              f"{'**' + str(lost) + '** ⚠' if lost else '0'}")
        if lost:
            print("\n**⚠ requests were lost** — an accepted request "
                  "neither delivered a result nor remains queued; the "
                  "failover/idempotence contract is broken.  This is a "
                  "bug, not load.")
        if s.get("shed"):
            print("\n_shedding engaged: clients received retry-after "
                  "rejections while the fleet was past its admission "
                  "watermarks — raise capacity or relax the SLO if "
                  "this recurs under normal load._")


def deploy_table(deploys: list[dict]) -> None:
    """Render the schema /15 deployment ledger (``kind="deploy"``,
    paddle_tpu/deploy/controller.py): one row per rollout attempt with
    its export/swap/total timings — a rolled-back or failed attempt is
    flagged loudly, because a fleet that silently stops taking weight
    pushes is a serving incident, not a detail."""
    if not deploys:
        return
    print("\n## Deployments\n")
    print("| attempt | checkpoint | outcome | export ms | swap ms "
          "| total ms |")
    print("|---|---|---|---|---|---|")
    bad = []
    for r in deploys:
        outcome = r.get("outcome", "-")
        if outcome != "deployed":
            bad.append(r)
            outcome = f"**{outcome}** ⚠"
        print(f"| {r.get('attempt', '?')} | `{r.get('checkpoint', '-')}` "
              f"| {outcome} | {_fmt(r.get('export_ms'))} "
              f"| {_fmt(r.get('swap_ms'))} | {_fmt(r.get('total_ms'))} |")
    ok = len(deploys) - len(bad)
    print(f"\n**{len(deploys)} rollout attempt(s)** · {ok} deployed · "
          f"{len(bad)} failed/rolled back")
    for r in bad:
        print(f"\n**⚠ {r.get('outcome')}**: `{r.get('checkpoint')}` "
              f"(attempt {r.get('attempt', '?')}) — "
              f"{r.get('error', 'no error recorded')}.  A rollback means "
              f"the fleet kept serving the PREVIOUS weights; if every "
              f"attempt for a checkpoint fails it is marked bad and the "
              f"next checkpoint deploys over it.")


def autoscale_table(events: list[dict]) -> None:
    """Render the schema /15 autoscale stream (``kind="autoscale"``,
    paddle_tpu/deploy/autoscaler.py + arbiter.py): one row per scale
    action and per pool shift — the chaos-ramp bench's evidence that
    the fleet followed the load curve both ways."""
    if not events:
        return
    print("\n## Autoscaling\n")
    print("| event | detail |")
    print("|---|---|")
    ups = downs = 0
    for r in events:
        ev = r.get("event", "-")
        if ev == "scale_up":
            ups += 1
            detail = (f"replica {r.get('replica', '?')} added "
                      f"({r.get('reason', '?')}) in "
                      f"{_fmt(r.get('scale_ms'))} ms")
        elif ev == "scale_down":
            downs += 1
            detail = (f"replica {r.get('replica', '?')} retired "
                      f"({r.get('reason', '?')}), "
                      f"{r.get('requeued', 0)} request(s) re-queued, in "
                      f"{_fmt(r.get('scale_ms'))} ms")
        elif ev in ("pool_borrow", "pool_return"):
            detail = (f"{r.get('reason', '?')} — pool now "
                      f"{r.get('trainer_hosts', '?')} trainer / "
                      f"{r.get('serving_hosts', '?')} serving host(s)")
        else:
            detail = str({k: v for k, v in r.items()
                          if k not in ("event", "kind", "schema",
                                       "ts", "host")})
        print(f"| {ev} | {detail} |")
    if ups or downs:
        print(f"\n**{ups} scale-up(s) · {downs} scale-down(s)** — "
              f"scale-downs drain through the failover re-queue path, "
              f"so they never lose requests.")


def _pctl(vals: list[float], q: float) -> float:
    """Nearest-rank-with-interpolation percentile over raw values (the
    per-request serve records carry exact latencies, so no bucket
    estimate is needed here)."""
    vs = sorted(vals)
    if len(vs) == 1:
        return vs[0]
    rank = (q / 100.0) * (len(vs) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(vs) - 1)
    return vs[lo] + (vs[hi] - vs[lo]) * (rank - lo)


def serving_table(serves: list[dict], summaries: list[dict]) -> None:
    """Render the schema /4 serving stream: per-request latency
    percentiles (TTFT / TPOT / queue wait / total) from the
    ``kind="serve"`` records, plus the engine's own histogram rollup
    (``serve_summary``) when present."""
    if not serves and not summaries:
        return
    print("\n## Serving latency\n")
    if serves:
        toks = sum(r.get("new_tokens", 0) for r in serves)
        cached = sum(r.get("cached_tokens", 0) for r in serves)
        chunks = sum(r.get("prefill_chunks", 0) for r in serves)
        extra = ""
        if cached:
            extra += f" · {cached} prompt tokens from prefix cache"
        if chunks:
            extra += f" · {chunks} prefill chunks"
        print(f"**{len(serves)} requests** · {toks} generated "
              f"tokens{extra}\n")
        print("| metric | count | p50 ms | p99 ms | max ms |")
        print("|---|---|---|---|---|")
        for field, label in (("ttft_ms", "TTFT"), ("tpot_ms", "TPOT"),
                             ("queue_wait_ms", "queue wait"),
                             ("total_ms", "total")):
            vals = [float(r[field]) for r in serves if field in r]
            if not vals:
                continue
            print(f"| {label} | {len(vals)} | {_pctl(vals, 50):,.2f} "
                  f"| {_pctl(vals, 99):,.2f} | {max(vals):,.2f} |")
    for s in summaries[-1:]:  # the newest rollup wins
        rows = s.get("summary") or {}
        if rows:
            print("\n_engine histogram rollup (bucket-interpolated):_\n")
            print("| histogram | count | p50 ms | p99 ms | max ms |")
            print("|---|---|---|---|---|")
            for name, h in rows.items():
                print(f"| {name} | {h.get('count', '-')} "
                      f"| {_fmt(h.get('p50'))} | {_fmt(h.get('p99'))} "
                      f"| {_fmt(h.get('max'))} |")
        p = s.get("prefix")
        if p:
            print("\n_prefix cache (schema /14):_\n")
            print("| prefix_hit_rate | hit tokens | prompt tokens "
                  "| prefill_chunks | evictions | cached pages "
                  "| recompute FLOPs saved |")
            print("|---|---|---|---|---|---|---|")
            print(f"| {p.get('hit_rate', 0):.2%} "
                  f"| {p.get('hit_tokens', 0)} "
                  f"| {p.get('prompt_tokens', 0)} "
                  f"| {s.get('prefill_chunks', 0)} "
                  f"| {p.get('evictions', 0)} "
                  f"| {p.get('cached_pages', 0)} "
                  f"| {p.get('flops_saved', 0):,.3g} |")
        elif s.get("prefill_chunks"):
            print(f"\n_{s['prefill_chunks']} incremental prefill "
                  "passes (chunked prefill on, prefix cache off)._")
        if s.get("rejected_admissions"):
            print(f"\n_⚠ {s['rejected_admissions']} admission attempts "
                  "blocked on pages/budget — requests queued while the "
                  "cache was full; grow num_pages or max_concurrent_"
                  "tokens if TTFT p99 matters more than memory._")


def preflight_table(records: list[dict],
                    steps: list[dict] | None = None) -> None:
    """Render the schema /7 static-analysis stream: one row per
    ``trainer --preflight`` / analysis run, with a loud flag on any run
    that was not clean — a program that failed its preflight must not
    read as a healthy run."""
    if not records:
        return
    print("\n## Preflight (static analysis)\n")
    print("| config | clean | findings | suppressed | by rule |")
    print("|---|---|---|---|---|")
    dirty = []
    for r in records:
        clean = r.get("clean", not r.get("findings"))
        if not clean:
            dirty.append(r)
        rules = ", ".join(f"{k}×{v}" for k, v in
                          (r.get("by_rule") or {}).items()) or "-"
        print(f"| {r.get('config') or '-'} | {'yes' if clean else '**NO** ⚠'} "
              f"| {r.get('findings', 0)} | {r.get('suppressed', 0)} "
              f"| {rules} |")
    if dirty:
        ids = "; ".join(i for r in dirty for i in (r.get("ids") or [])[:4])
        print(f"\n**⚠ {len(dirty)} preflight run(s) failed** — the "
              f"program carries statically detectable hazards "
              f"({ids}); fix them or baseline them with a reason "
              f"before trusting the run.")
    _memory_budget_table([r for r in records if r.get("memory")])
    _static_cost_table([r for r in records if r.get("cost")], steps or [])


def _memory_budget_table(records: list[dict]) -> None:
    """The schema /9 GL-P-MEM budget table: static per-device byte
    accounting of each preflighted step (params + zero-mode optimizer
    slots + activation liveness), the future sharding/kernel PR's
    citable byte-count assertion."""
    if not records:
        return
    print("\n### Memory budget (GL-P-MEM, static per device)\n")
    print("| config | zero | dp | params MB | opt MB | acts MB "
          "| total MB | activations via |")
    print("|---|---|---|---|---|---|---|---|")
    for r in records:
        m = r["memory"]
        print(f"| {r.get('config') or '-'} | {m.get('zero', 0)} "
              f"| {m.get('dp', 1)} "
              f"| {_fmt(m.get('params_bytes', 0) / 1e6)} "
              f"| {_fmt(m.get('opt_state_bytes', 0) / 1e6)} "
              f"| {_fmt(m.get('activation_bytes', 0) / 1e6)} "
              f"| **{_fmt(m.get('total_bytes', 0) / 1e6)}** "
              f"| {m.get('activation_source', '-')} |")
    vmem = [(r.get("config"), k) for r in records
            for k in (r["memory"].get("pallas_vmem") or ())]
    if vmem:
        print("\n| config | pallas kernel | VMEM MB |")
        print("|---|---|---|")
        for cfg, k in vmem:
            print(f"| {cfg or '-'} | {k.get('kernel')} "
                  f"| {_fmt(k.get('bytes', 0) / 1e6)} |")


def _measured_for(run: str, steps: list[dict]) -> tuple:
    """Median measured (step_ms, mfu_pct) of the step records that match
    a preflight record's run — a single-run stream matches regardless of
    the name (the common local flow: preflight, then train, one file)."""
    runs = {r.get("run", "train") for r in steps}
    mine = [r for r in steps
            if r.get("run", "train") == run or len(runs) == 1]
    ms = sorted(r["step_ms"] for r in mine
                if isinstance(r.get("step_ms"), (int, float)))
    mfu = sorted(r["mfu_pct"] for r in mine
                 if isinstance(r.get("mfu_pct"), (int, float))
                 and r["mfu_pct"] > 0)
    return (ms[len(ms) // 2] if ms else None,
            mfu[len(mfu) // 2] if mfu else None)


def _static_cost_table(records: list[dict], steps: list[dict]) -> None:
    """The schema /13 GL-P-COST roofline table: predicted step_ms / MFU
    per preflighted config vs the measured medians when a matching step
    stream exists, ⚠-flagging rows under the MFU target with the named
    bottleneck — the static claim and the measured truth side by side."""
    if not records:
        return
    print("\n### Static cost (GL-P-COST roofline)\n")
    print("| config | profile | pred step ms | pred MFU % | meas step ms "
          "| meas MFU % | bottleneck |")
    print("|---|---|---|---|---|---|---|")
    below = []
    for r in records:
        c = r["cost"]
        meas_ms, meas_mfu = _measured_for(r.get("run", "preflight"), steps)
        mfu = c.get("mfu_pct")
        cell = _fmt(mfu)
        bottleneck = c.get("bottleneck", "-")
        if isinstance(mfu, (int, float)) and mfu < MFU_TARGET_PCT:
            cell += " ⚠"
            below.append((r.get("config") or "-", mfu, bottleneck))
        print(f"| {r.get('config') or '-'} | {c.get('profile', '-')} "
              f"| {_fmt(c.get('step_ms'))} | {cell} "
              f"| {_fmt(meas_ms) if meas_ms is not None else '-'} "
              f"| {_fmt(meas_mfu) if meas_mfu is not None else '-'} "
              f"| {bottleneck} |")
    if below:
        names = "; ".join(f"{cfg} ({mfu:.1f}%, {b})"
                          for cfg, mfu, b in below)
        print(f"\n**⚠ {len(below)} config(s) predicted below the "
              f"{MFU_TARGET_PCT:.0f}% MFU target:** {names} — the named "
              f"bottleneck is where the next batching/fusion/sharding "
              f"change should land.")


def trace_table(profiles: list[dict]) -> None:
    """Render the schema /11 live-introspection stream: one block per
    ``--profile_steps`` capture (``kind="profile"``) with the tracer's
    per-phase duration table — p50/p99/total per phase name — and a
    loud flag on any fence or queue phase consuming more than 20% of
    the step phase's total time (the host is stalling on the device
    fence, or requests are parked in admission: the deferred-fencing /
    admission knobs are the lever)."""
    if not profiles:
        return
    print("\n## Trace spans (windowed device profiles)\n")
    for r in profiles:
        window = f"steps [{r.get('start_step', '?')}, " \
                 f"{r.get('end_step', '?')})"
        print(f"**profile** · {window} · wall "
              f"{_fmt(r.get('wall_ms'))} ms · trace "
              f"`{r.get('trace_dir', '-')}`\n")
        spans = r.get("spans") or {}
        if not spans:
            print("_no spans recorded in the window (run with "
                  "--trace_spans for the phase table)_")
            continue
        step_total = (spans.get("step") or {}).get("total_ms", 0.0)
        print("| phase | count | p50 ms | p99 ms | total ms "
              "| of step |")
        print("|---|---|---|---|---|---|")
        hot = []
        for name, s in spans.items():
            share = (s.get("total_ms", 0.0) / step_total
                     if step_total else None)
            cell = f"{share * 100:.1f}%" if share is not None else "-"
            flagged = (share is not None and share > 0.2
                       and ("fence" in name or "queue" in name))
            if flagged:
                cell += " ⚠"
                hot.append((name, share))
            print(f"| {name} | {s.get('count', '-')} "
                  f"| {_fmt(s.get('p50_ms'))} | {_fmt(s.get('p99_ms'))} "
                  f"| {_fmt(s.get('total_ms'))} | {cell} |")
        for name, share in hot:
            what = ("the deferred-fence drain is eating the step — "
                    "raise --sync_period or shrink the readback"
                    if "fence" in name else
                    "requests sit in admission — grow pages/slots or "
                    "shed earlier")
            print(f"\n**⚠ `{name}` is {share * 100:.0f}% of step "
                  f"time** — {what}.")


def goodput_table(ledgers: list[dict]) -> None:
    """Render the schema /12 goodput ledger (``kind="ledger"``,
    telemetry/goodput.py): the wall-clock account — one row per badput
    bucket with its share of wall — plus the serving cost-per-token
    split when the run served.  Buckets above 10% of wall are flagged:
    they are the lever the ledger exists to point at."""
    if not ledgers:
        return
    print("\n## Goodput\n")
    for r in ledgers:
        wall = r.get("wall_s") or 0.0
        frac = r.get("goodput_fraction")
        print(f"**ledger** · wall {_fmt(wall)} s · goodput "
              f"**{frac * 100:.1f}%**" if frac is not None
              else f"**ledger** · wall {_fmt(wall)} s")
        buckets = r.get("buckets_s") or {}
        if buckets:
            print("\n| bucket | seconds | of wall |")
            print("|---|---|---|")
            hot = []
            for name, secs in buckets.items():
                share = secs / wall if wall else 0.0
                cell = f"{share * 100:.1f}%"
                if share > 0.10 and name not in ("compute",):
                    cell += " ⚠"
                    hot.append((name, share))
                print(f"| {name} | {_fmt(secs, 3)} | {cell} |")
            if hot:
                names = ", ".join(f"`{n}` ({s * 100:.0f}%)"
                                  for n, s in hot)
                print(f"\n**⚠ badput over 10% of wall-clock:** {names} "
                      f"— the levers this ledger points at.")
        serving = r.get("serving") or {}
        if serving.get("cost_per_token_s") is not None:
            print("\n| cost per token | seconds |")
            print("|---|---|")
            for k, label in (("cost_per_token_s", "total (compute)"),
                             ("cost_per_token_prefill_s", "prefill"),
                             ("cost_per_token_decode_s", "decode"),
                             ("cost_per_token_queue_s", "queue")):
                if serving.get(k) is not None:
                    print(f"| {label} | {serving[k]:.6g} |")
            print(f"\n_{_fmt(serving.get('tokens', 0), 0)} tokens · "
                  f"KV-page occupancy "
                  f"{_fmt(serving.get('kv_page_s'))} page·s_")


MFU_TARGET_PCT = 50.0  # the ROADMAP north-star floor


def bench_table(rows: list[dict]) -> None:
    if not rows:
        return
    print("\n## Bench rows\n")
    print("| metric | value | MFU % |")
    print("|---|---|---|")
    below = []
    for r in rows:
        if "metric" not in r:
            continue
        val = f"{r.get('value', '-')} {r.get('unit', '')}".strip()
        mfu = r.get("mfu_pct", "-")
        cell = str(mfu)
        if isinstance(mfu, (int, float)) and mfu < MFU_TARGET_PCT:
            cell += " ⚠"
            below.append((r["metric"], mfu))
        print(f"| {r['metric']} | **{val}** | {cell} |")
    # the TPP fused-kernel ablation sub-rows: speedup + which path is
    # trusted (bit-identical trajectory vs tolerance-bounded)
    abl = [r for r in rows
           if str(r.get("metric", "")).endswith("fused_ablation_speedup")
           and "unfused_ms" in r]
    if abl:
        print("\n### Fused-kernel ablation (TPP)\n")
        print("| workload | unfused ms | fused ms | speedup | trajectory |")
        print("|---|---|---|---|---|")
        for r in abl:
            traj = ("bit-identical" if r.get("trajectory_identical")
                    else f"≤{r.get('trajectory_max_rel_diff', 0):.1e} rel")
            print(f"| {r['metric'].replace('_fused_ablation_speedup', '')} "
                  f"| {_fmt(r.get('unfused_ms'))} "
                  f"| {_fmt(r.get('fused_ms'))} "
                  f"| **{_fmt(r.get('value'))}x** | {traj} |")
    if below:
        names = ", ".join(f"{m} ({v}%)" for m, v in below)
        print(f"\n**⚠ {len(below)} row(s) below the {MFU_TARGET_PCT:.0f}% "
              f"MFU target:** {names} — candidates for the next fused-"
              f"kernel/batching pass.")


def main(argv: list[str]) -> int:
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 2
    last = None
    if "--last" in argv:
        i = argv.index("--last")
        last = int(argv[i + 1])
        argv = argv[:i] + argv[i + 2:]
    records = load(argv[0])
    steps = [r for r in records if r.get("kind") == "step"]
    faults = [r for r in records if r.get("kind") == "fault"]
    recoveries = [r for r in records if r.get("kind") == "recovery"]
    serves = [r for r in records if r.get("kind") == "serve"]
    serve_summaries = [r for r in records
                       if r.get("kind") == "serve_summary"]
    elastics = [r for r in records if r.get("kind") == "elastic_event"]
    fleets = [r for r in records if r.get("kind") == "fleet"]
    preflights = [r for r in records if r.get("kind") == "preflight"]
    profiles = [r for r in records if r.get("kind") == "profile"]
    ledgers = [r for r in records if r.get("kind") == "ledger"]
    deploys = [r for r in records if r.get("kind") == "deploy"]
    autoscales = [r for r in records if r.get("kind") == "autoscale"]
    bench = [r for r in records
             if r.get("kind") == "bench" or
             ("metric" in r and "kind" not in r)]  # pre-schema bench rows
    print(f"# Telemetry summary — {argv[0]}\n")
    if steps:
        by_run: dict[str, list] = {}
        for r in steps:
            by_run.setdefault(r.get("run", "train"), []).append(r)
        for run, rs in by_run.items():
            print(f"## Steps — run `{run}`\n")
            step_table(rs, last=last)
        comm_table(steps)
    recovery_table(faults, recoveries)
    elastic_table(elastics)
    fleet_table(fleets)
    deploy_table(deploys)
    autoscale_table(autoscales)
    serving_table(serves, serve_summaries)
    preflight_table(preflights, steps)
    trace_table(profiles)
    goodput_table(ledgers)
    bench_table(bench)
    if not steps and not bench and not faults and not recoveries \
            and not serves and not serve_summaries and not elastics \
            and not fleets and not preflights and not profiles \
            and not ledgers and not deploys and not autoscales:
        print("_no step, fault, serve or bench records found_")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
