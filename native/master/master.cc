// Elastic input-dispatch master service.
//
// Native C++ equivalent of the reference's Go master
// (go/master/service.go): a dataset is partitioned into tasks; trainers
// pull tasks, report completion or failure; timed-out or failed tasks are
// re-queued up to a failure cap; state snapshots to disk (atomic rename)
// and recovers on restart, so a restarted master resumes mid-pass.  The
// etcd control plane of the reference maps to local snapshot files here —
// on TPU pods the scheduler provides process placement, so the queue
// service itself is the only piece that must survive.
//
// Protocol: newline-delimited text over TCP, one command per line.
//   SET <n>            then n payload lines       -> OK <n_tasks>
//   GET                -> TASK <id> <epoch> <payload> | WAIT | DONE
//   FIN <id> <epoch>   -> OK | STALE
//   FAIL <id> <epoch>  -> OK | STALE
//   RESET              (done -> todo, next pass)   -> OK
//   STAT               -> STAT <todo> <pending> <done> <failed>
//   PING               -> PONG
//   STOP               -> OK (server exits)
// Payloads are opaque strings without '\n' (task payloads are usually
// "file:chunk_begin:chunk_end" specs from the recordio reader).
//
// Flags: --port N  --timeout-ms N  --failure-max N  --snapshot PATH
// With --snapshot, state is persisted on mutation (throttled to one flush
// per 100 ms) and recovered at startup (pending tasks are re-queued as
// todo, mirroring go/master/service.go recover()).

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <deque>
#include <map>
#include <sstream>
#include <string>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

struct Task {
  int id;
  int epoch;        // bumped on every dispatch; stale FIN/FAIL are ignored
  int num_failure;
  std::string payload;
};

struct PendingTask {
  Task task;
  Clock::time_point deadline;
};

struct State {
  std::deque<Task> todo;
  std::map<int, PendingTask> pending;  // by task id
  std::vector<Task> done;
  std::vector<Task> failed;
  int next_id = 0;
};

struct Config {
  int port = 0;
  int timeout_ms = 30000;
  int failure_max = 3;
  std::string snapshot_path;
};

State g_state;
Config g_cfg;
bool g_running = true;
bool g_dirty = false;        // state changed since the last snapshot flush
Clock::time_point g_last_snapshot = Clock::now();

// ---------- snapshot / recover (file-based etcd analog) ----------

void WriteTask(FILE* f, const Task& t) {
  fprintf(f, "%d %d %d %zu\n", t.id, t.epoch, t.num_failure,
          t.payload.size());
  fwrite(t.payload.data(), 1, t.payload.size(), f);
  fputc('\n', f);
}

bool ReadTask(FILE* f, Task* t) {
  size_t len;
  if (fscanf(f, "%d %d %d %zu", &t->id, &t->epoch, &t->num_failure, &len) !=
      4)
    return false;
  fgetc(f);  // the newline after the header
  t->payload.resize(len);
  if (fread(&t->payload[0], 1, len, f) != len) return false;
  fgetc(f);
  return true;
}

void SnapshotNow() {
  if (g_cfg.snapshot_path.empty()) return;
  std::string tmp = g_cfg.snapshot_path + ".tmp";
  FILE* f = fopen(tmp.c_str(), "w");
  if (!f) return;
  // pending tasks are persisted as todo: a recovered master cannot know
  // whether their workers survived, so it re-dispatches them
  fprintf(f, "%d %zu\n", g_state.next_id,
          g_state.todo.size() + g_state.pending.size());
  for (const auto& t : g_state.todo) WriteTask(f, t);
  for (const auto& kv : g_state.pending) WriteTask(f, kv.second.task);
  fprintf(f, "%zu\n", g_state.done.size());
  for (const auto& t : g_state.done) WriteTask(f, t);
  fprintf(f, "%zu\n", g_state.failed.size());
  for (const auto& t : g_state.failed) WriteTask(f, t);
  fclose(f);
  rename(tmp.c_str(), g_cfg.snapshot_path.c_str());
  g_dirty = false;
  g_last_snapshot = Clock::now();
}

// Mutations mark the state dirty; the poll loop flushes at most every
// 100 ms.  Re-writing the whole file per GET/FIN would make dispatch
// O(total_tasks); bounded staleness is fine because recovery already
// tolerates re-dispatching in-flight tasks.
void Snapshot() { g_dirty = true; }

void MaybeFlushSnapshot() {
  if (g_dirty && Clock::now() - g_last_snapshot >=
                     std::chrono::milliseconds(100))
    SnapshotNow();
}

bool Recover() {
  if (g_cfg.snapshot_path.empty()) return false;
  FILE* f = fopen(g_cfg.snapshot_path.c_str(), "r");
  if (!f) return false;
  State s;
  size_t n;
  if (fscanf(f, "%d %zu", &s.next_id, &n) != 2) {
    fclose(f);
    return false;
  }
  fgetc(f);
  Task t;
  for (size_t i = 0; i < n; i++)
    if (ReadTask(f, &t)) s.todo.push_back(t);
  if (fscanf(f, "%zu", &n) == 1) {
    fgetc(f);
    for (size_t i = 0; i < n; i++)
      if (ReadTask(f, &t)) s.done.push_back(t);
  }
  if (fscanf(f, "%zu", &n) == 1) {
    fgetc(f);
    for (size_t i = 0; i < n; i++)
      if (ReadTask(f, &t)) s.failed.push_back(t);
  }
  fclose(f);
  g_state = std::move(s);
  return true;
}

// ---------- queue operations (GetTask / TaskFinished semantics) ----------

void ProcessFailedTask(Task t) {
  t.num_failure++;
  if (t.num_failure > g_cfg.failure_max) {
    g_state.failed.push_back(t);  // discarded for this pass
  } else {
    g_state.todo.push_back(t);
  }
  Snapshot();
}

void CheckTimeouts() {
  auto now = Clock::now();
  std::vector<int> expired;
  for (const auto& kv : g_state.pending)
    if (kv.second.deadline <= now) expired.push_back(kv.first);
  for (int id : expired) {
    Task t = g_state.pending[id].task;
    g_state.pending.erase(id);
    ProcessFailedTask(t);
  }
}

std::string HandleLine(const std::string& line,
                       std::deque<std::string>* inbox) {
  std::istringstream ss(line);
  std::string cmd;
  ss >> cmd;
  if (cmd == "PING") return "PONG";
  if (cmd == "SET") {
    int n = 0;
    ss >> n;
    int added = 0;
    // payload lines were buffered by the caller
    for (int i = 0; i < n && !inbox->empty(); i++, added++) {
      Task t;
      t.id = g_state.next_id++;
      t.epoch = 0;
      t.num_failure = 0;
      t.payload = inbox->front();
      inbox->pop_front();
      g_state.todo.push_back(t);
    }
    // SET acks imply durability (a lost dataset is not re-dispatchable by
    // anyone); GET/FIN/FAIL stay throttled — their loss only re-dispatches
    SnapshotNow();
    return "OK " + std::to_string(added);
  }
  if (cmd == "GET") {
    if (!g_state.todo.empty()) {
      Task t = g_state.todo.front();
      g_state.todo.pop_front();
      t.epoch++;
      PendingTask p{t, Clock::now() +
                           std::chrono::milliseconds(g_cfg.timeout_ms)};
      g_state.pending[t.id] = p;
      Snapshot();
      return "TASK " + std::to_string(t.id) + " " +
             std::to_string(t.epoch) + " " + t.payload;
    }
    if (!g_state.pending.empty()) return "WAIT";
    return "DONE";  // pass complete (or failed-out); RESET starts the next
  }
  if (cmd == "FIN" || cmd == "FAIL") {
    int id = -1, epoch = -1;
    ss >> id >> epoch;
    auto it = g_state.pending.find(id);
    if (it == g_state.pending.end() || it->second.task.epoch != epoch)
      return "STALE";  // task was already re-dispatched (timeout) or done
    Task t = it->second.task;
    g_state.pending.erase(it);
    if (cmd == "FIN") {
      t.num_failure = 0;
      g_state.done.push_back(t);
      Snapshot();
    } else {
      ProcessFailedTask(t);
    }
    return "OK";
  }
  if (cmd == "RESET") {
    // next pass: completed and discarded tasks go back to todo
    for (auto& t : g_state.done) g_state.todo.push_back(t);
    for (auto& t : g_state.failed) {
      t.num_failure = 0;
      g_state.todo.push_back(t);
    }
    g_state.done.clear();
    g_state.failed.clear();
    Snapshot();
    return "OK";
  }
  if (cmd == "STAT") {
    return "STAT " + std::to_string(g_state.todo.size()) + " " +
           std::to_string(g_state.pending.size()) + " " +
           std::to_string(g_state.done.size()) + " " +
           std::to_string(g_state.failed.size());
  }
  if (cmd == "STOP") {
    g_running = false;
    return "OK";
  }
  return "ERR unknown command";
}

// ---------- connection handling (single-threaded poll loop) ----------

struct Conn {
  int fd;
  std::string inbuf;
  std::string outbuf;
  int expect_payloads = 0;        // >0 while consuming SET payload lines
  std::string pending_set_line;   // the SET line awaiting its payloads
  std::deque<std::string> payloads;
};

void ConsumeLines(Conn* c) {
  size_t pos;
  while ((pos = c->inbuf.find('\n')) != std::string::npos) {
    std::string line = c->inbuf.substr(0, pos);
    c->inbuf.erase(0, pos + 1);
    if (!line.empty() && line.back() == '\r') line.pop_back();
    if (c->expect_payloads > 0) {
      c->payloads.push_back(line);
      if (--c->expect_payloads == 0) {
        c->outbuf += HandleLine(c->pending_set_line, &c->payloads) + "\n";
        c->payloads.clear();
      }
      continue;
    }
    if (line.rfind("SET ", 0) == 0) {
      int n = atoi(line.c_str() + 4);
      if (n > 0) {
        c->expect_payloads = n;
        c->pending_set_line = line;
        continue;
      }
    }
    std::deque<std::string> empty;
    c->outbuf += HandleLine(line, &empty) + "\n";
  }
}

}  // namespace

int main(int argc, char** argv) {
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    auto next = [&]() { return (i + 1 < argc) ? argv[++i] : ""; };
    if (a == "--port") g_cfg.port = atoi(next());
    else if (a == "--timeout-ms") g_cfg.timeout_ms = atoi(next());
    else if (a == "--failure-max") g_cfg.failure_max = atoi(next());
    else if (a == "--snapshot") g_cfg.snapshot_path = next();
  }
  signal(SIGPIPE, SIG_IGN);
  if (Recover())
    fprintf(stderr, "master: recovered %zu todo tasks from snapshot\n",
            g_state.todo.size());

  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(g_cfg.port);
  if (bind(lfd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  socklen_t alen = sizeof(addr);
  getsockname(lfd, (sockaddr*)&addr, &alen);
  listen(lfd, 64);
  // the chosen port goes to stdout so a parent process can read it
  printf("PORT %d\n", ntohs(addr.sin_port));
  fflush(stdout);

  std::map<int, Conn> conns;
  while (g_running) {
    std::vector<pollfd> pfds;
    pfds.push_back({lfd, POLLIN, 0});
    for (auto& kv : conns) {
      short ev = POLLIN;
      if (!kv.second.outbuf.empty()) ev |= POLLOUT;
      pfds.push_back({kv.first, ev, 0});
    }
    poll(pfds.data(), pfds.size(), 50);
    CheckTimeouts();
    MaybeFlushSnapshot();
    if (pfds[0].revents & POLLIN) {
      int cfd = accept(lfd, nullptr, nullptr);
      if (cfd >= 0) {
        setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        fcntl(cfd, F_SETFL, fcntl(cfd, F_GETFL) | O_NONBLOCK);
        conns[cfd] = Conn{cfd};
      }
    }
    std::vector<int> closed;
    for (size_t i = 1; i < pfds.size(); i++) {
      int fd = pfds[i].fd;
      Conn& c = conns[fd];
      if (pfds[i].revents & (POLLIN | POLLHUP | POLLERR)) {
        char buf[4096];
        ssize_t r = recv(fd, buf, sizeof(buf), 0);
        if (r == 0 || (r < 0 && errno != EAGAIN && errno != EWOULDBLOCK)) {
          closed.push_back(fd);
          continue;
        }
        if (r < 0) r = 0;
        c.inbuf.append(buf, r);
        ConsumeLines(&c);
      }
      if (!c.outbuf.empty()) {
        ssize_t w = send(fd, c.outbuf.data(), c.outbuf.size(), MSG_NOSIGNAL);
        if (w > 0) c.outbuf.erase(0, w);
        else if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK)
          closed.push_back(fd);
      }
    }
    for (int fd : closed) {
      close(fd);
      conns.erase(fd);
    }
  }
  SnapshotNow();
  for (auto& kv : conns) close(kv.first);
  close(lfd);
  return 0;
}
