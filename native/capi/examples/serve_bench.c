/* Multi-threaded serving benchmark for the C inference ABI — the
 * measured answer to "does create_shared_param give real concurrency?"
 * (reference pattern: capi/gradient_machine.h:68, one shared-param
 * machine per serving thread).
 *
 * Usage: serve_bench <merged_model> <rows> <threads> <iters> [--use_cpu]
 * Creates one origin machine + (threads-1) shared-param machines (all
 * aliasing ONE loaded artifact), runs <iters> forwards of a <rows>-row
 * batch on each thread, prints aggregate forwards/s and rows/s.
 */
#define _POSIX_C_SOURCE 199309L
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <time.h>

#include "../paddle_capi.h"

#define CHECK(stmt)                                      \
  do {                                                   \
    paddle_error e = (stmt);                             \
    if (e != kPD_NO_ERROR) {                             \
      fprintf(stderr, "FAIL %s -> %d\n", #stmt, (int)e); \
      exit(1);                                           \
    }                                                    \
  } while (0)

typedef struct {
  paddle_gradient_machine machine;
  uint64_t rows, dim, iters;
} WorkerArgs;

static void* worker(void* argp) {
  WorkerArgs* a = (WorkerArgs*)argp;
  paddle_matrix input = paddle_matrix_create(a->rows, a->dim);
  for (uint64_t r = 0; r < a->rows; r++) {
    float* row;
    CHECK(paddle_matrix_get_row(input, r, &row));
    for (uint64_t c = 0; c < a->dim; c++)
      row[c] = (float)((r * 31 + c * 7) % 97) / 97.0f;
  }
  paddle_matrix outs[8];
  for (uint64_t i = 0; i < a->iters; i++) {
    uint64_t n_out = 8;
    CHECK(paddle_gradient_machine_forward(a->machine, &input, 1, outs,
                                          &n_out));
    for (uint64_t o = 0; o < n_out; o++) paddle_matrix_destroy(outs[o]);
  }
  paddle_matrix_destroy(input);
  return NULL;
}

static double now_sec(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

int main(int argc, char** argv) {
  if (argc < 5) {
    fprintf(stderr, "usage: %s model.tar rows threads iters [--use_cpu]\n",
            argv[0]);
    return 2;
  }
  uint64_t rows = strtoull(argv[2], NULL, 10);
  int threads = atoi(argv[3]);
  uint64_t iters = strtoull(argv[4], NULL, 10);
  if (rows == 0 || threads <= 0 || threads > 1024 || iters == 0) {
    fprintf(stderr, "rows/threads/iters must be positive (threads <= 1024)\n");
    return 2;
  }

  CHECK(paddle_init(argc - 1, argv + 1));

  paddle_gradient_machine origin;
  CHECK(paddle_gradient_machine_load_from_path(&origin, argv[1]));
  uint64_t dim;
  CHECK(paddle_gradient_machine_get_input_dim(origin, 0, &dim));

  WorkerArgs* args = calloc(threads, sizeof(WorkerArgs));
  args[0].machine = origin;
  for (int t = 1; t < threads; t++)
    CHECK(paddle_gradient_machine_create_shared_param(&args[t].machine,
                                                      origin));
  /* warm both paths (compile caches) */
  for (int t = 0; t < threads; t++) {
    args[t].rows = rows;
    args[t].dim = dim;
    args[t].iters = 1;
    worker(&args[t]);
    args[t].iters = iters;
  }

  pthread_t* tids = calloc(threads, sizeof(pthread_t));
  double t0 = now_sec();
  for (int t = 0; t < threads; t++)
    pthread_create(&tids[t], NULL, worker, &args[t]);
  for (int t = 0; t < threads; t++) pthread_join(tids[t], NULL);
  double dt = now_sec() - t0;

  double fwd = (double)threads * (double)iters;
  printf("threads=%d rows=%llu iters=%llu wall=%.3fs forwards/s=%.1f "
         "rows/s=%.0f\n",
         threads, (unsigned long long)rows, (unsigned long long)iters, dt,
         fwd / dt, fwd * (double)rows / dt);

  for (int t = 1; t < threads; t++)
    paddle_gradient_machine_destroy(args[t].machine);
  paddle_gradient_machine_destroy(origin);
  free(tids);
  free(args);
  return 0;
}
