/* Minimal C serving example — parity with the reference's
 * capi/examples/model_inference/dense/main.c: load a merged model, fill an
 * input matrix, forward, print probabilities.
 *
 * Usage: infer <merged_model> <input_dim> <n_rows> [--use_cpu]
 * Reads n_rows * input_dim float32 values from stdin (binary), writes each
 * output row as space-separated floats on stdout.
 */
#include <stdio.h>
#include <stdlib.h>

#include "../paddle_capi.h"

#define CHECK(stmt)                                              \
  do {                                                           \
    paddle_error e = (stmt);                                     \
    if (e != kPD_NO_ERROR) {                                     \
      fprintf(stderr, "FAIL %s -> %d\n", #stmt, (int)e);         \
      exit(1);                                                   \
    }                                                            \
  } while (0)

int main(int argc, char** argv) {
  if (argc < 4) {
    fprintf(stderr, "usage: %s model.tar dim rows\n", argv[0]);
    return 2;
  }
  uint64_t dim = strtoull(argv[2], NULL, 10);
  uint64_t rows = strtoull(argv[3], NULL, 10);

  CHECK(paddle_init(argc - 1, argv + 1)); /* forwards e.g. --use_cpu */

  paddle_gradient_machine machine;
  CHECK(paddle_gradient_machine_load_from_path(&machine, argv[1]));

  uint64_t n_inputs, model_dim;
  CHECK(paddle_gradient_machine_get_num_inputs(machine, &n_inputs));
  CHECK(paddle_gradient_machine_get_input_dim(machine, 0, &model_dim));
  if (n_inputs != 1 || model_dim != dim) {
    fprintf(stderr, "model wants %llu inputs of dim %llu\n",
            (unsigned long long)n_inputs, (unsigned long long)model_dim);
    return 1;
  }

  paddle_matrix input = paddle_matrix_create(rows, dim);
  for (uint64_t r = 0; r < rows; r++) {
    float* row;
    CHECK(paddle_matrix_get_row(input, r, &row));
    if (fread(row, sizeof(float), dim, stdin) != dim) {
      fprintf(stderr, "short read on stdin\n");
      return 1;
    }
  }

  paddle_matrix outs[8];
  uint64_t n_out = 8;
  CHECK(paddle_gradient_machine_forward(machine, &input, 1, outs, &n_out));

  for (uint64_t o = 0; o < n_out; o++) {
    uint64_t h, w;
    CHECK(paddle_matrix_get_shape(outs[o], &h, &w));
    for (uint64_t r = 0; r < h; r++) {
      float* row;
      CHECK(paddle_matrix_get_row(outs[o], r, &row));
      for (uint64_t c = 0; c < w; c++) printf("%.6g ", row[c]);
      printf("\n");
    }
    paddle_matrix_destroy(outs[o]);
  }
  paddle_matrix_destroy(input);
  paddle_gradient_machine_destroy(machine);
  return 0;
}
