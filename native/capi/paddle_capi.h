/* C inference ABI — parity with the reference's paddle/capi
 * (gradient_machine.h:36-112, matrix.h, error.h): create a machine from a
 * merged model binary, feed dense float matrices, run forward, read the
 * output matrix.  Implementation: native/capi/paddle_capi.cc embeds
 * CPython and executes the model's serialized StableHLO (jax.export)
 * through paddle_tpu.capi_bridge, so serving links against ONE .so and
 * needs no model code.
 *
 * Thread-safety: entry points take the embedded interpreter's GIL for
 * marshalling; it is safe to call from N threads concurrently.  For
 * multi-threaded serving create one machine per thread with
 * paddle_gradient_machine_create_shared_param below — shared machines
 * alias ONE loaded artifact (weights are baked into the compiled
 * executable; the machine is a pure function), so there is no per-thread
 * weight copy.  Measured on a single-core host, 1->8 threads are
 * throughput-flat with <2% overhead (native/capi/examples/serve_bench.c,
 * BENCHMARKS.md); per-thread compute overlap on multi-core hosts is not
 * yet measured — the standard deployment there is one process per
 * worker (the artifact file shared via the OS page cache).
 */
#ifndef PADDLE_TPU_CAPI_H
#define PADDLE_TPU_CAPI_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef enum {
  kPD_NO_ERROR = 0,
  kPD_NULLPTR = 1,
  kPD_OUT_OF_RANGE = 2,
  kPD_PROTOBUF_ERROR = 3, /* bad model bytes (name kept for parity) */
  kPD_NOT_SUPPORTED = 4,
  kPD_UNDEFINED_ERROR = -1,
} paddle_error;

typedef void* paddle_gradient_machine;
typedef void* paddle_matrix;

/* Initialize the runtime (embedded interpreter). argc/argv may pass
 * runtime flags, e.g. "--use_cpu" to force the CPU backend in tests. */
paddle_error paddle_init(int argc, char** argv);

/* ---- matrix ---- */
paddle_matrix paddle_matrix_create(uint64_t height, uint64_t width);
paddle_error paddle_matrix_destroy(paddle_matrix mat);
paddle_error paddle_matrix_get_shape(paddle_matrix mat, uint64_t* height,
                                     uint64_t* width);
/* Returns a mutable pointer to row r (row-major float32). */
paddle_error paddle_matrix_get_row(paddle_matrix mat, uint64_t r,
                                   float** row);

/* ---- gradient machine (inference) ---- */
paddle_error paddle_gradient_machine_create_for_inference_with_parameters(
    paddle_gradient_machine* machine, void* merged_model, uint64_t size);
paddle_error paddle_gradient_machine_load_from_path(
    paddle_gradient_machine* machine, const char* path);
/* in: array of n_in matrices (one per data layer, order = meta.json);
 * out: *n_out output matrices written to outs[0..] (caller destroys). */
paddle_error paddle_gradient_machine_forward(paddle_gradient_machine machine,
                                             paddle_matrix* in,
                                             uint64_t n_in,
                                             paddle_matrix* outs,
                                             uint64_t* n_out);
/* New machine sharing ORIGIN's loaded artifact (reference
 * gradient_machine.h:68 create_shared_param): no weight duplication —
 * the weights live once inside the compiled executable both handles
 * alias. Use one shared machine per serving thread. */
paddle_error paddle_gradient_machine_create_shared_param(
    paddle_gradient_machine* machine, paddle_gradient_machine origin);
paddle_error paddle_gradient_machine_destroy(paddle_gradient_machine machine);
/* Introspection: input count and per-input feature dim (meta.json order). */
paddle_error paddle_gradient_machine_get_num_inputs(
    paddle_gradient_machine machine, uint64_t* n);
paddle_error paddle_gradient_machine_get_input_dim(
    paddle_gradient_machine machine, uint64_t i, uint64_t* dim);

#ifdef __cplusplus
}
#endif
#endif /* PADDLE_TPU_CAPI_H */
