// C inference ABI implementation — embedded CPython calling
// paddle_tpu.capi_bridge (see paddle_capi.h for the contract).
//
// The reference implements this layer in C++ against its GradientMachine
// (paddle/capi/gradient_machine.cpp); here the "machine" is a serialized
// StableHLO program executed by the Python runtime, and this file is only
// marshalling: float buffers in, float buffers out.

#include "paddle_capi.h"

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstring>
#include <string>
#include <vector>

namespace {

struct Matrix {
  uint64_t height;
  uint64_t width;
  std::vector<float> data;
};

struct Machine {
  long handle;  // paddle_tpu.capi_bridge machine handle
};

bool g_initialized = false;

// Run fn while holding the GIL (paddle_init leaves the GIL released so
// multiple C threads can call in; see PyEval_SaveThread below).
class GILGuard {
 public:
  GILGuard() : state_(PyGILState_Ensure()) {}
  ~GILGuard() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

PyObject* Bridge() {
  static PyObject* mod = nullptr;
  if (!mod) mod = PyImport_ImportModule("paddle_tpu.capi_bridge");
  return mod;
}

}  // namespace

extern "C" {

paddle_error paddle_init(int argc, char** argv) {
  if (g_initialized) return kPD_NO_ERROR;
  for (int i = 0; i < argc; i++) {
    if (strcmp(argv[i], "--use_cpu") == 0) {
      setenv("JAX_PLATFORMS", "cpu", 1);
    }
  }
  if (!Py_IsInitialized()) Py_InitializeEx(0);
  {
    GILGuard gil;
    if (!Bridge()) {
      PyErr_Print();
      return kPD_UNDEFINED_ERROR;
    }
  }
  // release the GIL acquired by Py_Initialize so callers' threads can
  // each take it via PyGILState_Ensure
  if (PyGILState_Check()) PyEval_SaveThread();
  g_initialized = true;
  return kPD_NO_ERROR;
}

paddle_matrix paddle_matrix_create(uint64_t height, uint64_t width) {
  auto* m = new Matrix{height, width, std::vector<float>(height * width)};
  return m;
}

paddle_error paddle_matrix_destroy(paddle_matrix mat) {
  if (!mat) return kPD_NULLPTR;
  delete static_cast<Matrix*>(mat);
  return kPD_NO_ERROR;
}

paddle_error paddle_matrix_get_shape(paddle_matrix mat, uint64_t* height,
                                     uint64_t* width) {
  if (!mat) return kPD_NULLPTR;
  auto* m = static_cast<Matrix*>(mat);
  if (height) *height = m->height;
  if (width) *width = m->width;
  return kPD_NO_ERROR;
}

paddle_error paddle_matrix_get_row(paddle_matrix mat, uint64_t r,
                                   float** row) {
  if (!mat || !row) return kPD_NULLPTR;
  auto* m = static_cast<Matrix*>(mat);
  if (r >= m->height) return kPD_OUT_OF_RANGE;
  *row = m->data.data() + r * m->width;
  return kPD_NO_ERROR;
}

paddle_error paddle_gradient_machine_create_for_inference_with_parameters(
    paddle_gradient_machine* machine, void* merged_model, uint64_t size) {
  if (!machine || !merged_model) return kPD_NULLPTR;
  if (!g_initialized) return kPD_UNDEFINED_ERROR;
  GILGuard gil;
  PyObject* ret = PyObject_CallMethod(
      Bridge(), "create_machine", "y#", static_cast<char*>(merged_model),
      static_cast<Py_ssize_t>(size));
  if (!ret) {
    PyErr_Print();
    return kPD_PROTOBUF_ERROR;
  }
  long h = PyLong_AsLong(ret);
  Py_DECREF(ret);
  *machine = new Machine{h};
  return kPD_NO_ERROR;
}

paddle_error paddle_gradient_machine_create_shared_param(
    paddle_gradient_machine* machine, paddle_gradient_machine origin) {
  if (!machine || !origin) return kPD_NULLPTR;
  if (!g_initialized) return kPD_UNDEFINED_ERROR;
  auto* orig = static_cast<Machine*>(origin);
  GILGuard gil;
  PyObject* ret = PyObject_CallMethod(Bridge(), "create_shared_machine", "l",
                                      orig->handle);
  if (!ret) {
    PyErr_Print();
    return kPD_UNDEFINED_ERROR;
  }
  long h = PyLong_AsLong(ret);
  Py_DECREF(ret);
  *machine = new Machine{h};
  return kPD_NO_ERROR;
}

paddle_error paddle_gradient_machine_load_from_path(
    paddle_gradient_machine* machine, const char* path) {
  if (!machine || !path) return kPD_NULLPTR;
  FILE* f = fopen(path, "rb");
  if (!f) return kPD_NULLPTR;
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  if (size <= 0 || size > (1L << 33)) {  // dirs give -1; cap at 8 GiB
    fclose(f);
    return kPD_PROTOBUF_ERROR;
  }
  fseek(f, 0, SEEK_SET);
  std::vector<char> buf(size);
  if (fread(buf.data(), 1, size, f) != static_cast<size_t>(size)) {
    fclose(f);
    return kPD_UNDEFINED_ERROR;
  }
  fclose(f);
  return paddle_gradient_machine_create_for_inference_with_parameters(
      machine, buf.data(), size);
}

paddle_error paddle_gradient_machine_forward(paddle_gradient_machine machine,
                                             paddle_matrix* in,
                                             uint64_t n_in,
                                             paddle_matrix* outs,
                                             uint64_t* n_out) {
  if (!machine || !in || !outs || !n_out) return kPD_NULLPTR;
  if (n_in == 0) return kPD_OUT_OF_RANGE;
  for (uint64_t i = 0; i < n_in; i++)
    if (!in[i]) return kPD_NULLPTR;
  auto* mach = static_cast<Machine*>(machine);
  GILGuard gil;

  uint64_t rows = static_cast<Matrix*>(in[0])->height;
  PyObject* bufs = PyList_New(n_in);
  for (uint64_t i = 0; i < n_in; i++) {
    auto* m = static_cast<Matrix*>(in[i]);
    if (m->height != rows) {
      Py_DECREF(bufs);
      return kPD_OUT_OF_RANGE;
    }
    PyList_SET_ITEM(
        bufs, i,
        PyBytes_FromStringAndSize(
            reinterpret_cast<const char*>(m->data.data()),
            static_cast<Py_ssize_t>(m->data.size() * sizeof(float))));
  }
  PyObject* ret = PyObject_CallMethod(Bridge(), "forward", "lOl",
                                      mach->handle, bufs,
                                      static_cast<long>(rows));
  Py_DECREF(bufs);
  if (!ret) {
    PyErr_Print();
    return kPD_UNDEFINED_ERROR;
  }
  Py_ssize_t n = PyList_Size(ret);
  if (*n_out < static_cast<uint64_t>(n)) {
    Py_DECREF(ret);
    return kPD_OUT_OF_RANGE;
  }
  *n_out = n;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* tup = PyList_GetItem(ret, i);  // (bytes, rows, cols)
    char* data;
    Py_ssize_t len;
    PyBytes_AsStringAndSize(PyTuple_GetItem(tup, 0), &data, &len);
    uint64_t orows = PyLong_AsUnsignedLongLong(PyTuple_GetItem(tup, 1));
    uint64_t ocols = PyLong_AsUnsignedLongLong(PyTuple_GetItem(tup, 2));
    auto* m = static_cast<Matrix*>(paddle_matrix_create(orows, ocols));
    memcpy(m->data.data(), data, len);
    outs[i] = m;
  }
  Py_DECREF(ret);
  return kPD_NO_ERROR;
}

paddle_error paddle_gradient_machine_get_num_inputs(
    paddle_gradient_machine machine, uint64_t* n) {
  if (!machine || !n) return kPD_NULLPTR;
  GILGuard gil;
  PyObject* r = PyObject_CallMethod(Bridge(), "num_inputs", "l",
                                    static_cast<Machine*>(machine)->handle);
  if (!r) {
    PyErr_Print();
    return kPD_UNDEFINED_ERROR;
  }
  *n = PyLong_AsUnsignedLongLong(r);
  Py_DECREF(r);
  return kPD_NO_ERROR;
}

paddle_error paddle_gradient_machine_get_input_dim(
    paddle_gradient_machine machine, uint64_t i, uint64_t* dim) {
  if (!machine || !dim) return kPD_NULLPTR;
  GILGuard gil;
  PyObject* r = PyObject_CallMethod(Bridge(), "input_dim", "ll",
                                    static_cast<Machine*>(machine)->handle,
                                    static_cast<long>(i));
  if (!r) {
    PyErr_Print();
    return kPD_OUT_OF_RANGE;
  }
  *dim = PyLong_AsUnsignedLongLong(r);
  Py_DECREF(r);
  return kPD_NO_ERROR;
}

paddle_error paddle_gradient_machine_destroy(paddle_gradient_machine machine) {
  if (!machine) return kPD_NULLPTR;
  auto* mach = static_cast<Machine*>(machine);
  if (g_initialized && Py_IsInitialized()) {
    GILGuard gil;
    PyObject* r = PyObject_CallMethod(Bridge(), "destroy_machine", "l",
                                      mach->handle);
    Py_XDECREF(r);
  }
  delete mach;
  return kPD_NO_ERROR;
}

}  // extern "C"
